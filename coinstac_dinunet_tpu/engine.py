"""In-process federation engine (simulator) + single-site runner.

The reference has **no network code**: an external COINSTAC engine (Node.js)
invokes each node with ``cache``/``input``/``state`` dicts and relays each
node's ``output`` JSON plus dropped transfer files (SURVEY.md §0).
:class:`InProcessEngine` reproduces that contract in one Python process — it
is the multi-node test backbone (SURVEY §4 "golden-file protocol tests" gap)
and the engine-transport benchmark driver.  :class:`SiteRunner` is the
single-site no-engine debug harness (≙ ref ``site_runner.py:8-45``).

Directory layout per site ``i`` under ``workdir``::

    site_<i>/            baseDirectory   (site's private data + inbox)
    site_<i>/out         outputDirectory
    remote_base/site_<i> site's transferDirectory == aggregator's inbox
    remote_xfer          aggregator's transferDirectory (broadcast outbox)
"""
import datetime
import math
import os
import shutil
import statistics
import threading
import time

import numpy as np

from . import config, telemetry, utils
from .config.keys import (
    Federation,
    Key,
    Live,
    LocalWire,
    Membership,
    Metric,
    Mode,
    Phase,
    RemoteWire,
)
from .telemetry import capture as _capture
from .data import COINNDataHandle
from .nodes import COINNLocal, COINNRemote
from .resilience import transport as wire_transport
from .resilience.chaos import ChaosSession
from .resilience.retry import RetryExhausted, RetryPolicy
from .trainer import COINNTrainer
from .utils import logger
from .utils.utils import performance_improved_, stop_training_
from .vision import plotter


class InvokeTimeout(RuntimeError):
    """A fresh-process (or daemon-worker) node invocation exceeded the
    engine's ``timeout``.  Typed so the retry/quorum machinery and
    ``telemetry doctor`` can attribute the failure; the message carries the
    partial stderr the process wrote before it was killed."""


#: test-only switch (ISSUE 14): force the run-ahead pipeline to drain its
#: reducer worker inside every round, right after the reduce is submitted —
#: every re-submission then sees the freshest broadcast and the schedule is
#: exactly the d=0 async one.  ``tests/test_async.py`` flips this to pin
#: that the pipeline machinery (reducer-worker offload, harvest, the
#: ``.stale`` alias rewrite of fresh outputs) is semantically transparent:
#: a d>=1 run under the switch must be score-identical to d=0 — which is
#: exactly the drain contract a barrier round relies on.
_PIPELINE_FORCE_DRAIN = False

#: broadcast keys a run-ahead re-submission strips: each is one-shot
#: round state (the update payload, barrier/broadcast side effects) that
#: the site already consumed when it first received this broadcast —
#: re-delivering them would double-apply the update.  Everything else
#: (phase, global_modes, the wire_round stamp the lag accounting rides on)
#: is carried verbatim.
_RUN_AHEAD_STRIP = (
    RemoteWire.UPDATE.value,
    RemoteWire.AVG_GRADS_FILE.value,
    RemoteWire.SAVE_CURRENT_AS_BEST.value,
    RemoteWire.PRETRAINED_WEIGHTS.value,
    RemoteWire.HEALTH.value,
    RemoteWire.ADMISSIONS.value,
)

#: broadcast keys that make a round ineligible for run-ahead: multi-
#: invocation sync protocols (powerSGD's P/Q phases, rankDAD payloads) and
#: run-level transitions count broadcasts exactly once by construction —
#: the engine falls back to blocking on the reducer instead of running
#: ahead of them.
_RUN_AHEAD_BLOCKERS = (
    RemoteWire.POWERSGD_PHASE.value,
    RemoteWire.POWERSGD_P_FILE.value,
    RemoteWire.POWERSGD_Q_FILE.value,
    RemoteWire.RANK1_FILE.value,
    RemoteWire.DAD_DATA_FILE.value,
    RemoteWire.DAD_REST_FILE.value,
    RemoteWire.GLOBAL_RUNS.value,
    RemoteWire.RESULTS_ZIP.value,
)


def load_inputspec(path, site_index=None):
    """Parse a COINSTAC simulator ``inputspec.json`` into plain args.

    The simulator format (ref ``site_runner.py:13-15``) is a list of per-site
    ``{key: {"value": ...}}`` dicts (or one such dict shared by all sites).
    ``site_index=None`` returns the list of per-site arg dicts; an int
    returns that site's args.
    """
    import json

    if os.path.isdir(path):
        path = os.path.join(path, "inputspec.json")
    with open(path) as f:
        spec = json.load(f)
    if isinstance(spec, dict):
        spec = [spec]

    def unwrap(site_spec):
        return {
            k: (v["value"] if isinstance(v, dict) and "value" in v else v)
            for k, v in site_spec.items()
        }

    sites = [unwrap(s) for s in spec]
    if site_index is None:
        return sites
    site_index = int(site_index)
    if not 0 <= site_index < len(sites):
        raise IndexError(
            f"site_index {site_index} out of range for {len(sites)}-site inputspec"
        )
    return sites[site_index]


def _engine_recorder(eng, chans):
    """Shared engine-lane recorder resolution (``telemetry.engine.jsonl``
    in the workdir): enabled when any of the engine's arg channels carries
    ``profile``/``telemetry`` — the same flags that enable the node-side
    recorders.  Re-checks cheaply until enabled (fresh-process engines only
    learn the flag from round 1's cache); caches the live recorder on the
    engine once built."""
    rec = getattr(eng, "_telemetry_rec", None)
    if rec is not None:
        return rec

    def on(d):
        # like _quorum_configured, the flag may sit nested in a ``*_args``
        # tier of a fresh-process engine's first_input — without this,
        # round-1 events (worker:start, the INIT invoke spans) would land
        # on a null recorder until the flag round-trips through the cache
        if not isinstance(d, dict):
            return False
        if d.get("profile") or d.get("telemetry"):
            return True
        return any(
            isinstance(v, dict) and (v.get("profile") or v.get("telemetry"))
            for k, v in d.items() if str(k).endswith("_args")
        )

    if any(on(c) for c in chans):
        eng._telemetry_rec = telemetry.Recorder("engine", out_dir=eng.workdir)
        return eng._telemetry_rec
    return telemetry.NULL_RECORDER


class InProcessEngine:
    """Runs N site nodes + one aggregator, relaying outputs and files.

    ``inputspec`` (path to a COINSTAC simulator ``inputspec.json`` or its
    directory) seeds per-site args exactly like the simulator would; explicit
    ``**args`` / ``site_args`` win over the spec.
    """

    def __init__(self, workdir, n_sites, trainer_cls=COINNTrainer,
                 dataset_cls=None, datahandle_cls=COINNDataHandle,
                 remote_trainer_cls=None, learner_cls=None, reducer_cls=None,
                 site_args=None, inputspec=None, fault_plan=None, **args):
        # deterministic fault injection (resilience/chaos.py): None → the
        # no-op singleton, so the fault-free hot path costs one attribute
        # lookup per hook point
        self.chaos = ChaosSession.from_spec(fault_plan)
        # spec args sit BELOW explicit **args and site_args (lowest priority)
        self.site_spec = {}
        if inputspec is not None:
            per_site = load_inputspec(inputspec)
            if len(per_site) != 1 and len(per_site) != int(n_sites):
                raise ValueError(
                    f"inputspec has {len(per_site)} per-site entries but the "
                    f"engine was built with n_sites={n_sites}; only a "
                    "single-entry spec broadcasts to every site"
                )
            for i in range(int(n_sites)):
                self.site_spec[f"site_{i}"] = per_site[min(i, len(per_site) - 1)]
        self.workdir = str(workdir)
        self.n_sites = int(n_sites)
        self.trainer_cls = trainer_cls
        self.remote_trainer_cls = remote_trainer_cls or trainer_cls
        self.dataset_cls = dataset_cls
        self.datahandle_cls = datahandle_cls
        self.learner_cls = learner_cls
        self.reducer_cls = reducer_cls
        self.args = args
        self.site_args = site_args or {}

        self.site_ids = [f"site_{i}" for i in range(self.n_sites)]
        self.site_caches = {s: {} for s in self.site_ids}
        self.remote_cache = {}
        self.site_states = {}
        for s in self.site_ids:
            base = os.path.join(self.workdir, s)
            xfer = os.path.join(self.workdir, "remote_base", s)
            outd = os.path.join(base, "out")
            for d in (base, xfer, outd):
                os.makedirs(d, exist_ok=True)
            self.site_states[s] = {
                "baseDirectory": base,
                "outputDirectory": outd,
                "transferDirectory": xfer,
                "clientId": s,
            }
        self.remote_state = {
            "baseDirectory": os.path.join(self.workdir, "remote_base"),
            "transferDirectory": os.path.join(self.workdir, "remote_xfer"),
            "outputDirectory": os.path.join(self.workdir, "remote_out"),
        }
        for d in self.remote_state.values():
            os.makedirs(d, exist_ok=True)

        self.site_inputs = {s: {} for s in self.site_ids}
        self.rounds = 0
        self.success = False
        self.last_remote_out = {}
        self.dead_sites = set()
        self.site_failures = {}
        # elastic membership (ISSUE 15, federation/membership.py): sites
        # gracefully retired (never invoked again — distinct from dead:
        # their exit cost no retry cycle and fired no site_died), joins
        # queued via add_site but not yet requested from the aggregator,
        # joins whose admission request is on the wire, leaves queued via
        # remove_site (the site's next input carries the ``leave`` flag),
        # and the member asked to ship warm-start weights this round
        self.left_sites = set()
        self._pending_join = {}       # site -> "join" | "rejoin"
        self._awaiting_admission = {}  # site -> "join" | "rejoin"
        self._pending_leave = set()
        self._sync_donors = set()
        # churn-plan ops racing an in-flight transition on the same site
        # (a leave while its rejoin admission is on the wire, a rejoin
        # while its graceful leave is pending) are deferred — re-tried at
        # the next round's churn hook — never skipped: admission takes
        # rounds, and a per-round plan schedules against the INTENDED
        # roster, not the in-flight one
        self._deferred_ops = []
        # per-site last round output, kept for the chaos replay faults
        # (``stale`` replays it in place of a fresh invocation; ``reappear``
        # redelivers a dead site's last message one round after its death)
        self._last_site_outs = {}
        # seed the quorum roster with the FULL consortium: a site dying in
        # round 0 must be judged (and recorded) against the original
        # n_sites, not silently absorbed into a shrunken roster
        # (COINNRemote._init_runs setdefaults, so this wins)
        self.remote_cache["all_sites"] = list(self.site_ids)
        # staleness-bounded async round state (_step_round_async): the
        # bounded invocation pool, per-site pending futures, and the
        # submission round of each site's last FRESH delivered output —
        # lazily built, zero cost on the lockstep path
        self._async_cfg = None
        self._async_pool = None
        self._async_pending = {}   # site -> (submit_round, future, policy)
        self._async_last_sub = {}  # site -> submit round of last fresh out
        self._async_snapshots = {}  # site -> {output file key -> snapshot}
        # run-ahead pipelining state (ISSUE 14, Federation.RUN_AHEAD):
        # the dedicated reducer worker + its in-flight reduce futures
        # (FIFO; harvested opportunistically, drained at barriers), the
        # broadcast stamp each site last CONSUMED (a full input re-delivers
        # a broadcast exactly once — re-submitting the same stamp would
        # double-apply its update, so it is stripped instead), the per-site
        # run-ahead depth, round-tagged snapshot generations, and the set
        # of sites delivered fresh this round (the re-submission roster)
        self._reduce_pool = None
        self._reduce_pending = []  # [(reduce_round, future, submit_t)]
        self._async_consumed = {}  # site -> wire_round stamp last consumed
        self._run_ahead_depth = {}  # site -> consecutive run-ahead submits
        self._async_snap_gen = {}   # site -> snapshot generation counter
        self._async_snap_files = {}  # site -> {gen: [alias paths]}
        self._async_fresh = set()
        # per-site recent invoke wall-times (grace basis).  The FIRST
        # completed invocation per site is dropped: it carries the one-off
        # cold start (worker spawn, imports, first compiles) and would
        # inflate the grace window for the whole run.  Pool threads append
        # while the engine thread computes the grace median — the lock
        # keeps the deques from mutating mid-iteration (dinulint tier-5
        # conc-unguarded-shared-write discipline)
        self._async_invoke_hist = {}
        self._async_warm = set()
        self._async_hist_lock = threading.Lock()

    # ------------------------------------------------------------- telemetry
    def _recorder(self):
        """The engine driver's own timeline lane: per-round spans around
        every node invocation and the file relay, so the merged Perfetto
        view shows where a federated round's wall-clock actually goes.
        See :func:`_engine_recorder` for the enable contract."""
        chans = [self.args, *self.site_args.values(), *self.site_spec.values(),
                 *self.site_caches.values()]
        chans += list(getattr(self, "first_input", {}).values() or [])
        return _engine_recorder(self, chans)

    # --------------------------------------------------------- site dropout
    def _alive_site_ids(self):
        return [
            s for s in self.site_ids
            if s not in self.dead_sites and s not in self.left_sites
        ]

    # ----------------------------------------------- elastic membership (15)
    def add_site(self, site_id=None, site_args=None, first_input=None):
        """Queue a mid-run JOIN (or rejoin of a dead/left site).  The site
        is provisioned now (directories, fresh cache, state) but becomes
        invocable only after the aggregator's admission handshake: at the
        next steady-state round the engine submits an admission request
        (``cache['membership_requests']``) carrying a donor member's
        round-alignment sync and asks that donor to ship its live weights
        (``membership_sync``); when the admission record comes back on the
        broadcast (:attr:`~.config.keys.RemoteWire.ADMISSIONS`), the
        joiner is activated and invoked from the following round — so a
        joiner admitted at round r contributes to round r+1's reduce,
        exactly once.  Returns the site id."""
        if site_id is None:
            ix = len(self.site_ids)
            while f"site_{ix}" in self.site_states:
                ix += 1
            site_id = f"site_{ix}"
        site_id = str(site_id)
        if (site_id in self._alive_site_ids()
                or site_id in self._pending_join
                or site_id in self._awaiting_admission):
            raise ValueError(f"{site_id} is already a member (or joining)")
        rejoin = site_id in self.dead_sites or site_id in self.left_sites
        base = os.path.join(self.workdir, site_id)
        xfer = os.path.join(self.workdir, "remote_base", site_id)
        outd = os.path.join(base, "out")
        for d in (base, xfer, outd):
            os.makedirs(d, exist_ok=True)
        self.site_states[site_id] = {
            "baseDirectory": base,
            "outputDirectory": outd,
            "transferDirectory": xfer,
            "clientId": site_id,
        }
        # a fresh incarnation: any state of a previous life is gone (the
        # whole reason its old payloads must be refused by roster epoch)
        self.site_caches[site_id] = {}
        self.site_inputs.setdefault(site_id, {})
        if site_args:
            self.site_args[site_id] = dict(site_args)
        fi = getattr(self, "first_input", None)
        if fi is not None:
            if first_input is not None:
                fi[site_id] = dict(first_input)
            elif site_id not in fi and fi:
                # fresh-process engines resolve node args via first_input:
                # a joiner inherits the consortium template by default
                fi[site_id] = dict(next(iter(fi.values())))
            self._first_done.discard(site_id)
        self._pending_join[site_id] = "rejoin" if rejoin else "join"
        return site_id

    def remove_site(self, site_id, graceful=True):
        """Remove a member mid-run.  ``graceful`` (default) injects the
        ``leave`` flag into the site's next round input: it computes one
        final flagged contribution, the reducer counts it, the aggregator
        retires it (roster epoch bump) and the engine never invokes it
        again — no ``site_died``, no retry cycle.  ``graceful=False``
        drops the site immediately (the quorum machinery treats it like a
        death, minus the failed invocation)."""
        site_id = str(site_id)
        if site_id not in self._alive_site_ids():
            raise ValueError(f"{site_id} is not an alive member")
        if graceful:
            self._pending_leave.add(site_id)
            return
        self.dead_sites.add(site_id)
        self.site_failures[site_id] = "removed by operator"
        self._recorder().event(
            "site_died", cat="quorum", site=site_id,
            error="removed by operator", attempts=0,
            retries_exhausted=False,
        )

    def _membership_steady(self):
        """True when the federation is in the steady state a join can be
        admitted into: the last broadcast is a COMPUTATION round and every
        broadcast mode is TRAIN — the joiner then enters mid-epoch in
        lockstep (barrier/transition rounds defer the admission)."""
        out = self.last_remote_out or {}
        if out.get(RemoteWire.PHASE.value) != Phase.COMPUTATION.value:
            return False
        modes = set(
            (out.get(RemoteWire.GLOBAL_MODES.value) or {}).values()
        )
        return not modes or modes == {Mode.TRAIN.value}

    def _apply_membership_op(self, kind, site):
        """One churn-plan op against the live roster.  Returns True when
        the op is applied (or already satisfied), False when it must be
        DEFERRED — the same site has a transition in flight (admission on
        the wire, leave pending) that this op's precondition waits on.
        Raises ValueError only for genuine plan bugs (an op no amount of
        waiting can satisfy)."""
        in_flight_join = (site in self._pending_join
                          or site in self._awaiting_admission)
        if kind == "leave":
            # the in-flight check MUST come first: a rejoining site still
            # sits in left_sites until its admission activates, and the
            # already-left fast path would silently swallow this NEW leave
            if in_flight_join:
                return False  # joining: let the admission land first
            if site in self.left_sites or site in self._pending_leave:
                return True   # already left / leaving
            self.remove_site(site, graceful=True)
            return True
        # join / rejoin
        if in_flight_join:
            return True       # already on its way in
        if site in self._pending_leave:
            return False      # leaving: let the retirement land first
        if site in self._alive_site_ids():
            return True       # already a member — nothing to admit
        self.add_site(site)
        return True

    def _membership_round(self, rnd, rec):
        """The engine's churn hook, run at the top of every round: apply
        the chaos churn plan's join/leave/rejoin ops
        (:meth:`~.resilience.chaos.ChaosSession.membership_ops`) plus any
        ops deferred behind an in-flight transition, activate joiners
        whose admission arrived on the last broadcast, and submit pending
        admission requests during the steady state.  Under run-ahead
        pipelining any membership activity first drains the in-flight
        reduces — a membership round is a barrier."""
        ops = self._deferred_ops + list(self.chaos.membership_ops(rnd, rec))
        self._deferred_ops = []
        for kind, site in ops:
            try:
                if not self._apply_membership_op(kind, site):
                    self._deferred_ops.append((kind, site))
            except ValueError as exc:
                # a churn plan op racing the roster (double-join, leave of
                # a dead site) is a plan bug worth surfacing, not a crash
                logger.warn(f"churn plan op {kind}@{site} skipped: {exc}")
        pending = (self._pending_join or self._awaiting_admission
                   or self._pending_leave)
        if pending and self._reduce_pending:
            self._pipeline_drain(rec, reason="membership")
        admissions = (
            (self.last_remote_out or {}).get(RemoteWire.ADMISSIONS.value)
            or {}
        )
        for s in sorted(set(self._awaiting_admission) & set(admissions)):
            self._activate_joiner(s, rec)
        if self._pending_join and self._membership_steady():
            donor = next(iter(self._alive_site_ids()), None)
            if donor is not None:
                reqs = self.remote_cache.setdefault(Membership.REQUESTS, [])
                for s in sorted(self._pending_join):
                    sync = {
                        k: self.site_caches.get(donor, {}).get(k)
                        for k in ("cursor", "epoch", "mode")
                    }
                    reqs.append({
                        "op": self._pending_join[s], "site": s,
                        "sync": {
                            k: v for k, v in sync.items() if v is not None
                        },
                    })
                    self._awaiting_admission[s] = self._pending_join[s]
                self._pending_join = {}
                # the same round's donor invocation ships the live weights
                # the admission broadcast relays to the joiner's warm start
                self._sync_donors.add(donor)

    def _activate_joiner(self, s, rec):
        """The admission record for ``s`` arrived: the site becomes a
        live member.  The admission broadcast's files were relayed before
        the joiner was invocable, so the aggregator's outbox is replayed
        into its inbox here (catch-up relay), and its input is the
        admission broadcast itself — its first invocation enters at the
        steady-state COMPUTATION phase (``nodes/local.py`` join entry)."""
        op = self._awaiting_admission.pop(s, "join")
        rejoin = op == "rejoin" or s in self.dead_sites or s in self.left_sites
        self.dead_sites.discard(s)
        self.left_sites.discard(s)
        self.site_failures.pop(s, None)
        if s not in self.site_ids:
            self.site_ids.append(s)
        # fresh-incarnation bookkeeping: no replay record, no async
        # staleness history, no run-ahead depth may survive a rejoin
        self._last_site_outs.pop(s, None)
        self._async_last_sub.pop(s, None)
        self._async_consumed.pop(s, None)
        self._run_ahead_depth.pop(s, None)
        self._async_snapshots.pop(s, None)
        self._async_snap_gen.pop(s, None)
        self._async_snap_files.pop(s, None)
        with self._async_hist_lock:
            self._async_invoke_hist.pop(s, None)
            self._async_warm.discard(s)
        self._relay_to_site(s)
        self.site_inputs[s] = dict(self.last_remote_out)
        self._sync_admission(s)
        # no membership:* event here: the aggregator's admission
        # (membership.process_admissions) already emitted the one
        # roster-transition event — a second engine-lane emission would
        # double-count membership_changes_total and feed the live plane a
        # conflicting members= semantics (alive count vs roster size)
        logger.warn(
            f"membership: {s} {'re-joined' if rejoin else 'joined'} the "
            f"federation ({len(self._alive_site_ids())} alive members)"
        )

    def _sync_admission(self, s):
        """Refresh the joiner's admission sync to a donor member's CURRENT
        round alignment (cursor/epoch/mode) at activation time.  The
        request-time sync the admission broadcast carried is one wire
        round stale by the time the joiner's first invocation runs (the
        aggregator processed the admission during that round), and a
        one-step cursor skew would phase-shift the joiner's epoch barrier
        against the federation forever — the engine owns round alignment,
        so it re-stamps the sync with the donor's live cache here."""
        admissions = dict(
            self.site_inputs[s].get(RemoteWire.ADMISSIONS.value) or {}
        )
        adm = dict(admissions.get(s) or {})
        if not adm:
            return
        donor = next(
            (x for x in self._alive_site_ids() if x != s), None
        )
        if donor is None:
            return
        dc = self.site_caches.get(donor) or {}
        for k in ("cursor", "epoch", "mode"):
            if dc.get(k) is not None:
                adm[k] = dc[k]
        admissions[s] = adm
        self.site_inputs[s][RemoteWire.ADMISSIONS.value] = admissions

    def _relay_to_site(self, s):
        """Catch-up relay for a freshly activated joiner: the aggregator's
        whole outbox, manifest last (the same ordering contract as
        :meth:`_relay_broadcast`)."""
        xfer = self.remote_state["transferDirectory"]
        names = sorted(
            os.listdir(xfer),
            key=lambda f: (f == wire_transport.MANIFEST_NAME, f),
        )
        for f in names:
            wire_transport.atomic_copy(
                os.path.join(xfer, f),
                os.path.join(self.site_states[s]["baseDirectory"], f),
            )

    def _membership_input(self, s, inp):
        """Engine-brokered membership keys injected into one site's round
        input (see ``ENGINE_PROVIDED_KEYS``): the one-shot warm-start
        weight request for the donor, and the graceful-leave flag (which
        persists until the leaver's flagged contribution is delivered)."""
        extra = {}
        if s in self._sync_donors:
            self._sync_donors.discard(s)
            extra["membership_sync"] = True
        if s in self._pending_leave:
            extra["leave"] = True
        if not extra:
            return inp
        return {**inp, **extra}

    def _finalize_leavers(self, site_outs, rec):
        """Move every site whose delivered output carried the LEAVING flag
        out of the invocable roster — the aggregator retired it this round
        (after the reduce counted its final contribution).  Runs before
        the broadcast fan-out so a left site gets no next-round input."""
        for s in sorted(self._pending_leave):
            out = site_outs.get(s)
            if out is not None and out.get(LocalWire.LEAVING.value):
                self._pending_leave.discard(s)
                self.left_sites.add(s)
                self.site_inputs.pop(s, None)
                # the aggregator's retirement (membership.retire_leaving)
                # already emitted the one membership:leave event — see
                # _activate_joiner for why the engine lane stays silent
                logger.warn(
                    f"membership: {s} left gracefully "
                    f"({len(self._alive_site_ids())} alive members remain)"
                )

    def _quorum_configured(self):
        """True when site_quorum was configured on ANY of this engine's
        channels: engine **args (in-process), a node cache that already
        resolved it (fresh-process, after round 1), or the fresh-process
        engine's ``first_input`` (before round 1) — either at the top
        level or nested in a ``*_args`` tier of the 3-tier arg pipeline."""

        def has_quorum(d):
            if not isinstance(d, dict):
                return False
            if d.get("site_quorum"):
                return True
            return any(
                isinstance(v, dict) and v.get("site_quorum")
                for k, v in d.items() if str(k).endswith("_args")
            )

        if has_quorum(self.args):
            return True
        if any(has_quorum(c) for c in self.site_caches.values()):
            return True
        fi = getattr(self, "first_input", None)
        return bool(fi) and any(has_quorum(v) for v in fi.values())

    def _site_failure(self, s, exc, attempts=1):
        """A site's invocation raised (after ``attempts`` tries under the
        invoke retry policy).  Without ``site_quorum`` the failure
        propagates (reference-faithful all-site lockstep); with it, the site
        is marked dead and excluded from all subsequent rounds — the REMOTE
        enforces the actual quorum policy and the documented survivor-
        weighted semantics (``COINNRemote._check_quorum``).  The
        ``site_died`` event carries the attempt count so ``telemetry
        doctor`` can attribute the death to *exhausted retries* vs a *hard
        failure* with no retry configured."""
        if not self._quorum_configured():
            raise exc
        self.dead_sites.add(s)
        self.site_failures[s] = f"{type(exc).__name__}: {exc}"
        # exact attribution: RetryExhausted means the retry budget (attempt
        # count OR deadline) ran out — `attempts > 1` alone would misread a
        # deadline exhausted during attempt 1 as "no retry configured"
        self._recorder().event(
            "site_died", cat="quorum", site=s, error=self.site_failures[s],
            attempts=int(getattr(exc, "attempts", attempts)),
            retries_exhausted=isinstance(exc, RetryExhausted),
        )
        logger.warn(
            f"site {s} died mid-run ({self.site_failures[s]}) after "
            f"{attempts} invocation attempt(s); excluded from the remaining "
            "rounds (site_quorum set)"
        )

    # ---------------------------------------------------------- invoke retry
    def _target_config(self, target):
        """Merged configuration for ONE target, resolved over that target's
        own arg channels so a knob scoped to one site via
        ``site_args``/``inputspec`` never silently applies to another.
        Site priority mirrors node construction: ``site_args`` > engine
        ``**args`` > ``site_spec``, then the round-tripped cache and the
        fresh-process ``first_input``.  The remote scans every channel
        (mirroring ``_quorum_configured``) because its config can only
        arrive via a site's ``first_input`` before round 1 freezes
        ``shared_args`` into its cache.  Nested ``*_args`` tiers count.
        Shared by the invoke retry policy and the daemon engine's worker
        restart policy (:mod:`.federation.daemon`)."""
        if target == "remote":
            chans = [self.args, self.remote_cache,
                     *self.site_args.values(), *self.site_spec.values(),
                     *self.site_caches.values()]
            chans += list(getattr(self, "first_input", {}).values() or [])
        else:
            fi = getattr(self, "first_input", {}) or {}
            chans = [self.site_args.get(target, {}), self.args,
                     self.site_spec.get(target, {}),
                     self.site_caches.get(target, {}), fi.get(target, {})]
        cfg = {}
        for chan in chans:
            if not isinstance(chan, dict):
                continue
            for k, v in chan.items():
                if isinstance(v, dict) and str(k).endswith("_args"):
                    for k2, v2 in v.items():
                        cfg.setdefault(k2, v2)
                else:
                    cfg.setdefault(k, v)
        return cfg

    def _invoke_policy(self, target):
        """The invocation retry policy for ONE target (re-invoking a node
        has side effects the operator opts into per-site — default is 1
        attempt, retry off)."""
        return RetryPolicy.for_invoke(self._target_config(target))

    def _invoke_with_retry(self, policy, attempt_fn, target, rec):
        """Run one node invocation under the retry policy: every retry first
        heals chaos-damaged payloads (the deterministic 'relay completed'
        moment for out-of-process readers) and lands an ``invoke:retry``
        event on the engine lane."""

        def on_retry(exc, attempt, delay):
            # only heal damage blocking THIS node's reads — a retry of one
            # node must not cancel faults aimed at another
            healed = self.chaos.heal_for_retry(rec, target=target)
            rec.event(
                "invoke:retry", cat="invoke", target=str(target),
                attempt=attempt, delay=round(delay, 4), healed=healed,
                error=f"{type(exc).__name__}: {exc}"[:300],
            )

        return policy.run(
            attempt_fn, retryable=(Exception,),
            describe=f"invoke {target}", on_retry=on_retry,
        )

    def site_data_dir(self, site_id, data_dir="data"):
        d = os.path.join(self.site_states[site_id]["baseDirectory"], data_dir)
        os.makedirs(d, exist_ok=True)
        return d

    # ----------------------------------------------------- chaos replay faults
    def _stale_replay(self, rnd, s, rec):
        """A matching ``stale`` fault replays the site's previous round
        output in place of a fresh invocation (its payload files in the
        transfer directory are the untouched previous round's — exactly a
        delayed duplicate of the site→aggregator message).  Returns the
        replayed output dict, or None to invoke normally."""
        if not self.chaos.enabled:
            return None
        prev = self._last_site_outs.get(s)
        if prev is None:
            return None
        if self.chaos.stale_fault(rnd, s, rec) is None:
            return None
        return dict(prev)

    def _finish_site_outputs(self, rnd, site_outs, rec, record=True):
        """Round barrier after the site loop, shared by both engines (the
        ordering is load-bearing and must not diverge between them):
        record every fresh output for future replay faults FIRST, then
        deliver the stale last output of sites whose ``reappear`` fault
        died one round earlier — the dropped-site-reappears scenario the
        aggregator's roster filtering must reject
        (``COINNRemote._check_quorum``).

        ``record=False`` (the run-ahead pipelined reduce, which runs this
        from the reducer worker thread) skips the replay-record update:
        the engine thread already recorded each fresh output at delivery,
        and a deferred reduce writing the table later could regress it
        below a newer delivery."""
        if record:
            self._last_site_outs.update(
                {s: dict(o) for s, o in site_outs.items()}
            )
        if not self.chaos.enabled:
            return
        for s in self.chaos.reappear_deliveries(rnd, rec):
            prev = self._last_site_outs.get(s)
            if prev is not None:
                site_outs[s] = dict(prev)

    # ------------------------------------------------------------- one round
    def _relay_broadcast(self, rnd, rec):
        """Relay aggregator transfer files into every surviving site's inbox
        — atomically (a reader can never observe a partial copy), with the
        chaos relay faults (drop/duplicate) applied per destination.

        Files relay in sorted order with ``.wire_manifest.json`` LAST: the
        destination's manifest must never describe payloads that have not
        been delivered yet (``os.listdir`` order is OS-arbitrary, so the
        old order could relay the manifest first and leave a window where a
        faulted payload is indistinguishable from one still mid-relay —
        the clobber-ordering window the tier-4 model checker audits)."""
        xfer = self.remote_state["transferDirectory"]
        names = sorted(
            os.listdir(xfer),
            key=lambda f: (f == wire_transport.MANIFEST_NAME, f),
        )
        for f in names:
            src = os.path.join(xfer, f)
            for s in self._alive_site_ids():
                dst = os.path.join(self.site_states[s]["baseDirectory"], f)
                fault = self.chaos.relay_fault(rnd, f, s, rec)
                if fault is not None and fault.kind == "drop_relay":
                    # the file never arrives this round; the repair (a retry
                    # heal performs the copy) models the relay completing
                    self.chaos.register_dropped_relay(src, dst, fault,
                                                      reader=s)
                    continue
                if fault is not None and fault.kind == "duplicate_delivery":
                    # a stale out-of-order duplicate clobbers the fresh copy
                    self.chaos.deliver_duplicate(src, dst, fault, s, rec)
                    continue
                wire_transport.atomic_copy(src, dst)

    # -------------------------------------------------------- invocation hooks
    # ``step_round`` below is the ONE engine round template every serial
    # engine shares (in-process, fresh-process, daemon): the chaos replay
    # faults, the invoke retry policy, quorum dropout, heartbeats, payload
    # faults and the relay broadcast all live there exactly once.  What
    # differs per engine is only HOW one node invocation attempt runs —
    # these three hooks.

    def _site_input(self, s):
        """The input dict for this round's invocation of site ``s``
        (computed ONCE per round, before the retry loop, so every retry
        attempt sees identical input).  Membership keys (the warm-start
        sync request, the graceful-leave flag) are injected here."""
        return self._membership_input(s, self.site_inputs[s])

    def _site_attempt(self, rnd, s, inp, rec):
        """ONE invocation attempt of site ``s``; returns its output dict.
        Raises on failure (the retry policy and quorum machinery in
        ``step_round`` handle it).  The chaos invoke fault fires INSIDE
        the span: a ``slow`` fault's sleep is the site's simulated compute
        and must show on the timeline (the ``wire_overlap_ratio`` metric
        and the async span-overlap tests read it)."""
        node = COINNLocal(
            cache=self.site_caches[s], input=inp, state=self.site_states[s],
            **{**self.site_spec.get(s, {}), **self.args,
               **self.site_args.get(s, {})},
        )
        # round pinned as a span attr: a pool-thread invocation may outlive
        # the round it was submitted in, and the ambient round context is
        # only read at span END — the explicit attr wins over it
        with rec.span(f"invoke:{s}", cat="invoke", round=rnd):
            self.chaos.invoke_fault(rnd, s, rec)
            return node(
                trainer_cls=self.trainer_cls,
                dataset_cls=self.dataset_cls,
                datahandle_cls=self.datahandle_cls,
                learner_cls=self.learner_cls,
            )["output"]

    def _remote_attempt(self, rnd, site_outs, rec):
        """ONE aggregator invocation attempt; returns its output dict and
        records ``success``.  Round pinned as a span attr: under run-ahead
        pipelining this runs on the reducer worker thread one round behind
        the engine's ambient round context."""
        self.chaos.invoke_fault(rnd, "remote", rec)
        remote = COINNRemote(
            cache=self.remote_cache, input=site_outs, state=self.remote_state,
        )
        with rec.span("invoke:remote", cat="invoke", round=rnd):
            result = remote(
                trainer_cls=self.remote_trainer_cls,
                reducer_cls=self.reducer_cls,
            )
        self.success = bool(result.get("success"))
        return result["output"]

    def _remote_and_relay(self, rnd, site_outs, rec, record_outs=True):
        """The round's wire half, shared by the lockstep and async paths:
        replay-fault bookkeeping barrier, aggregator invocation (under its
        retry policy), and the broadcast relay.  Returns the aggregator's
        output dict.  The run-ahead pipeline runs this whole tail on the
        dedicated reducer worker (``record_outs=False`` — the replay
        record was already written at delivery on the engine thread)."""
        self._finish_site_outputs(rnd, site_outs, rec, record=record_outs)
        if not site_outs:
            raise RuntimeError(
                "every site died; nothing to aggregate — failures: "
                f"{self.site_failures}"
            )

        remote_out = self._invoke_with_retry(
            self._invoke_policy("remote"),
            lambda: self._remote_attempt(rnd, site_outs, rec),
            "remote", rec,
        )
        rec.event(Live.HEARTBEAT, cat="engine", site="remote")
        self.last_remote_out = remote_out

        with rec.span("engine:relay", cat="relay", round=rnd):
            self._relay_broadcast(rnd, rec)
        return remote_out

    def step_round(self):
        """One full engine round: every site computes, files relay to the
        aggregator, the aggregator computes, its output + files relay back.

        With the async configuration present (``Federation.ASYNC_STALENESS``
        / ``Federation.ASYNC_POOL`` on any of the engine's arg channels) the
        round runs through :meth:`_step_round_async` instead: sites are
        invoked concurrently through a bounded pool and a straggler's last
        contribution may stand in for up to ``k`` rounds."""
        ac = self._async_config()
        if ac["enabled"]:
            return self._step_round_async(ac)
        rec = self._recorder()
        rnd = self.rounds + 1
        rec.set_context(round=rnd)
        self._membership_round(rnd, rec)
        site_outs = {}
        with self.chaos.activate(rec), rec.span("engine:round", cat="engine"):
            for s in self._alive_site_ids():
                replay = self._stale_replay(rnd, s, rec)
                if replay is not None:
                    site_outs[s] = replay
                    continue
                policy = self._invoke_policy(s)
                inp = self._site_input(s)

                def attempt(s=s, inp=inp):
                    return self._site_attempt(rnd, s, inp, rec)

                try:
                    site_outs[s] = self._invoke_with_retry(
                        policy, attempt, s, rec
                    )
                except Exception as exc:  # noqa: BLE001 — see _site_failure
                    self._site_failure(s, exc, attempts=policy.last_attempts)
                    continue
                # liveness pulse for the live ops plane (telemetry/live.py):
                # a site that stops completing invocations stops beating
                rec.event(Live.HEARTBEAT, cat="engine", site=s)
                # chaos payload damage happens AFTER the site committed its
                # outbound files — exactly where a truncated relay would
                self.chaos.payload_faults(
                    rnd, s, self.site_states[s]["transferDirectory"], rec
                )

            remote_out = self._remote_and_relay(rnd, site_outs, rec)
            self._finalize_leavers(site_outs, rec)
        rec.flush()
        self.site_inputs = {s: dict(remote_out) for s in self._alive_site_ids()}
        self.rounds += 1
        return site_outs, remote_out

    # ------------------------------------------------- async rounds (ISSUE 12)
    # Staleness-bounded async rounds, per computation/communication-
    # decoupled SGD (arXiv:1906.12043): every idle site is invoked through a
    # bounded thread pool, and a site still computing when the round's
    # reduce arrives may be represented by its LAST completed contribution
    # for up to k = Federation.ASYNC_STALENESS rounds — so one slow site no
    # longer gates the federation, and the aggregator's reduce + relay for
    # round r overlap the straggler computing what becomes its round-r+1
    # contribution.  The aggregator accepts the lagging ``wire_round`` echo
    # inside the window (nodes/remote.py::_check_lockstep_phases) and the
    # reducer down-weights it (parallel/reducer.py::_site_weights); the
    # tier-4 model checker's ``staleness_k`` action proves the relaxed
    # protocol's exactly-once invariants at the bound.
    #
    # Stand-ins are confined to the COMPUTATION steady state (every fresh
    # output this round in TRAIN mode with a reduce payload, and the stand-
    # in likewise): INIT/fold transitions and the validation/test barriers
    # stay strictly lockstep — the engine blocks on the straggler there, so
    # every barrier's score/epoch semantics are exactly the serial ones.

    #: bounded-pool ceiling; the in-process engine pins 1 (its nodes share
    #: the process-global ambient telemetry stack and the GIL — real
    #: concurrency comes from the process-backed engines, where the pool
    #: threads only do pipe/process I/O)
    _ASYNC_POOL_CAP = 1

    #: run-ahead depth ceiling (ISSUE 14): the in-process engine pins 0 —
    #: its aggregator node activates the process-global ambient telemetry
    #: stack, so the reduce tail cannot leave the engine thread; the
    #: process-backed engines (where the reduce is a pipe request to the
    #: warm aggregator worker) lift the cap
    _RUN_AHEAD_CAP = 0

    def _async_config(self):
        """Resolve the async round configuration once per engine, over the
        same arg channels as the quorum/retry knobs (``_target_config``):
        async mode is ON when any ``Federation`` async key is configured
        anywhere; ``k=0`` with pool 1 runs the async path in strict serial
        order (score-identical to the lockstep template — the parity
        contract of ``tests/test_async.py``).  ``run_ahead=0`` keeps the
        blocking wire tail bit-identical to the PR-12 schedule; ``d >= 1``
        (process-backed engines) decouples it onto the reducer worker."""
        if self._async_cfg is not None:
            return self._async_cfg
        cfg = self._target_config("remote")
        k_raw = cfg.get(Federation.ASYNC_STALENESS)
        pool_raw = cfg.get(Federation.ASYNC_POOL)
        ra_raw = cfg.get(Federation.RUN_AHEAD)
        enabled = (
            k_raw is not None or pool_raw is not None or ra_raw is not None
        )
        k = max(int(k_raw or 0), 0)
        d = max(int(ra_raw or 0), 0)
        if self._RUN_AHEAD_CAP is not None and d > self._RUN_AHEAD_CAP:
            # the aggregator's k + d window must mirror the horizon this
            # engine actually ENFORCES, not the raw configuration: clamp
            # the depth on every arg channel this engine feeds its nodes
            # from (resolved before any invocation, so the first round
            # freezes the clamped value into shared_args) — otherwise an
            # in-process run with run_ahead=1 would widen the refusal
            # boundary for a staleness its engine can never produce
            d = self._RUN_AHEAD_CAP
            for chan in (self.args, *self.site_args.values(),
                         *self.site_spec.values()):
                if not isinstance(chan, dict):
                    continue
                if Federation.RUN_AHEAD in chan:
                    chan[Federation.RUN_AHEAD] = d
                for kk, vv in chan.items():
                    if (isinstance(vv, dict) and str(kk).endswith("_args")
                            and Federation.RUN_AHEAD in vv):
                        vv[Federation.RUN_AHEAD] = d
            logger.warn(
                f"run_ahead={int(ra_raw or 0)} clamped to {d} on this "
                "engine (the in-process aggregator shares the ambient "
                "telemetry stack; run-ahead needs a process-backed "
                "engine) — the clamped depth is what shared_args freeze"
            )
        if pool_raw is not None:
            pool = max(int(pool_raw), 1)
        else:
            pool = self.n_sites if enabled else 1
        if self._ASYNC_POOL_CAP is not None:
            pool = min(pool, self._ASYNC_POOL_CAP)
        self._async_cfg = {
            "enabled": bool(enabled), "k": k, "pool": pool, "run_ahead": d,
            # with no explicit pool size the pool follows the LIVE roster
            # (elastic membership: a join grows it, ISSUE 15) instead of
            # freezing the founding n_sites
            "pool_auto": pool_raw is None,
        }
        return self._async_cfg

    def _async_pool_size(self, ac):
        """This round's invocation-pool ceiling: the configured size, or —
        when the operator set none — the live member count, so mid-run
        joins keep every site concurrently invocable (the resize is
        applied by :meth:`_ensure_async_pool`)."""
        if not ac.get("pool_auto"):
            return ac["pool"]
        size = max(len(self._alive_site_ids()), 1)
        if self._ASYNC_POOL_CAP is not None:
            size = min(size, self._ASYNC_POOL_CAP)
        return size

    def _ensure_async_pool(self, size):
        if self._async_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._async_pool = ThreadPoolExecutor(
                max_workers=int(size), thread_name_prefix="coinn-async"
            )
        elif int(size) > getattr(self._async_pool, "_max_workers", 0):
            # live resize for elastic membership: a mid-run join must not
            # queue behind the founding roster's pool ceiling.  Raising
            # ``_max_workers`` is sufficient — ThreadPoolExecutor spawns
            # threads lazily on submit up to the current ceiling, so the
            # next submission grows the pool (stdlib-stable since 3.8;
            # shrinking is never needed: an idle thread just parks).
            self._async_pool._max_workers = int(size)
        return self._async_pool

    def _ensure_reduce_pool(self):
        """The dedicated long-lived reducer worker (ISSUE 14): ONE thread
        that serializes the aggregator's reduce+relay tails in submission
        order while the engine thread keeps collecting and re-submitting
        site invocations.  For the daemon engine this thread only drives
        the frame pipe — the k-ary tree reduce itself streams inside the
        warm aggregator worker process."""
        if self._reduce_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._reduce_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="coinn-reducer"
            )
        return self._reduce_pool

    #: collect-phase grace: a round waits up to this multiple of the
    #: federation's TYPICAL invoke duration (median of per-site EMAs) for
    #: in-flight invocations before falling back to stand-ins — so a round
    #: always carries fresh contributions from every healthy site and only
    #: a genuine straggler (this factor or more behind its peers) is
    #: represented by its last payload
    _ASYNC_GRACE_FACTOR = 2.0

    def _async_attempt(self, policy, rnd, s, inp, rec):
        """One site invocation under its retry policy — the pool task.  The
        retry/heal machinery is the serial template's; only the calling
        thread differs.  The wall duration feeds the per-site recent-
        invoke window the collect-phase grace is derived from (first
        completed invocation per site skipped — cold start)."""

        def attempt():
            return self._site_attempt(rnd, s, inp, rec)

        t0 = time.monotonic()
        out = self._invoke_with_retry(policy, attempt, s, rec)
        dur = time.monotonic() - t0
        with self._async_hist_lock:
            if s in self._async_warm:
                from collections import deque

                self._async_invoke_hist.setdefault(
                    s, deque(maxlen=8)
                ).append(dur)
            else:
                self._async_warm.add(s)
        return out

    def _async_grace(self):
        """Seconds the collect phase waits for in-flight invocations: the
        grace factor times the cross-site median of each site's BEST
        recent invoke time.  The cross-site median keeps one straggler
        from inflating everyone's wait (its own slow samples only shape
        its own series); the within-site minimum estimates the site's
        UNCONTENDED compute — which is what the grace wants to measure.
        A site's recent-median would ratchet up under transient
        contention (under run-ahead pipelining the reduce tail overlaps
        site compute, and a handful of contended samples in every site's
        window once inflated the grace until the straggler never missed
        collect and the stand-in machinery silently disarmed — each round
        then paid the full straggler latency again); the best-recent
        basis washes a spike out with the first clean sample, while a
        sustained slowdown still raises every site's floor and keeps the
        wait adapting to genuine load.  None before any warm invocation
        completed (warm-up rounds block anyway)."""
        with self._async_hist_lock:
            per_site = [
                min(hist)
                for hist in self._async_invoke_hist.values() if hist
            ]
        if not per_site:
            return None
        return self._ASYNC_GRACE_FACTOR * statistics.median(per_site)

    def _async_standin_ok(self, s):
        """A straggler's last output can stand in only when it is a steady-
        state TRAIN contribution (phase COMPUTATION, mode TRAIN, reduce
        payload attached): barrier/transition outputs must never be
        replayed — their keys drive epoch/fold state the protocol counts
        exactly once."""
        prev = self._last_site_outs.get(s)
        return (
            prev is not None
            and prev.get("phase") == Phase.COMPUTATION.value
            and prev.get("mode") == Mode.TRAIN.value
            and bool(prev.get("reduce"))
        )

    def _async_steady(self, site_outs):
        """True when this round is in the COMPUTATION/TRAIN steady state as
        far as every FRESH output collected so far shows — the only regime
        stand-ins are allowed in.  Any barrier signal (a waiting mode, a
        phase transition, a non-computation broadcast) forces the round
        back to lockstep blocking.  At least one fresh output is required:
        a round of 100% stand-ins would re-reduce pure duplicates while
        the round counter advances (the pool-of-1 shape where every future
        is queued behind the straggler must block, not replay)."""
        if not site_outs:
            return False
        if self.last_remote_out.get("phase") != Phase.COMPUTATION.value:
            return False
        for out in site_outs.values():
            if out.get("phase") != Phase.COMPUTATION.value:
                return False
            if out.get("mode") != Mode.TRAIN.value:
                return False
        return True

    def _async_deliver(self, rnd, s, rec, site_outs):
        """Deliver site ``s``'s pending invocation (blocking if it has not
        finished): fresh output, heartbeat, payload faults — the serial
        template's per-site tail.  A failure flows to the quorum machinery
        exactly like the serial path."""
        q, fut, policy = self._async_pending.pop(s)
        try:
            out = fut.result()
        except Exception as exc:  # noqa: BLE001 — see _site_failure
            self._site_failure(s, exc, attempts=policy.last_attempts)
            return
        site_outs[s] = out
        self._async_last_sub[s] = q
        self._async_fresh.add(s)
        rec.event(Live.HEARTBEAT, cat="engine", site=s)
        if self._async_cfg and self._async_cfg["k"]:
            rec.metric(Metric.SITE_STALENESS, float(rnd - q), site=s)
        self.chaos.payload_faults(
            rnd, s, self.site_states[s]["transferDirectory"], rec
        )
        if self._async_cfg and (
            self._async_cfg["k"] or self._async_cfg["run_ahead"]
        ):
            self._async_snapshot_payloads(s, out)
        if self._async_cfg and self._async_cfg["run_ahead"]:
            # the replay/stand-in record commits at delivery, on the
            # ENGINE thread: the deferred reduce job skips it
            # (_finish_site_outputs record=False), so a reduce harvested
            # late can never regress the table below a newer delivery
            self._last_site_outs[s] = dict(out)

    def _async_snapshot_payloads(self, s, out):
        """Freeze a fresh contribution's payload files under stable
        ``<name>.stale`` aliases (same directory, atomic copy).  A later
        stand-in references the aliases instead of the live names: the
        straggler's NEXT invocation commits over the live names at an
        arbitrary moment, and without the alias the aggregator's mid-reduce
        load of the stale payload would race that commit (manifest/CRC
        mismatch → retry backoff on the round's critical path).  Alias
        copies carry the embedded v2 checksum and sit outside the
        directory manifest — 'no expectation', exactly like a not-yet-
        relayed file.

        Under run-ahead pipelining the aliases are GENERATION-tagged
        (``<name>.stale<g>``): the reduce consuming round r's alias may
        still be in flight on the reducer worker when round r+1's fresh
        delivery snapshots — an untagged alias would be overwritten under
        the mid-reduce read.  Generations older than the combined
        ``k + d`` horizon (plus slack) can no longer be referenced by any
        in-flight reduce or stand-in and are pruned."""
        xfer = self.site_states[s]["transferDirectory"]
        d = (self._async_cfg or {}).get("run_ahead", 0)
        gen = None
        if d:
            gen = self._async_snap_gen.get(s, 0) + 1
            self._async_snap_gen[s] = gen
        snaps, paths = {}, []
        for key, val in out.items():
            if not (isinstance(key, str) and key.endswith("_file")):
                continue
            if not isinstance(val, str):
                continue
            src = os.path.join(xfer, val)
            if not os.path.exists(src):
                continue
            alias = f"{val}.stale" if gen is None else f"{val}.stale{gen}"
            wire_transport.atomic_copy(src, os.path.join(xfer, alias))
            snaps[key] = alias
            paths.append(os.path.join(xfer, alias))
        self._async_snapshots[s] = snaps
        if gen is not None:
            files = self._async_snap_files.setdefault(s, {})
            files[gen] = paths
            horizon = self._async_cfg["k"] + d + 2
            for old in [g for g in files if g <= gen - horizon]:
                for p in files.pop(old):
                    try:
                        os.remove(p)
                    except OSError:
                        pass

    def _async_alias_out(self, s, out):
        """``out`` with every payload reference rewritten to the frozen
        alias of site ``s``'s last snapshot (idempotent — an already-
        aliased reference maps to itself)."""
        out = dict(out)
        for key, alias in self._async_snapshots.get(s, {}).items():
            if key in out:
                out[key] = alias
        return out

    def _async_standin_out(self, s):
        """The stand-in output dict for a straggling site: its last
        contribution with every payload reference rewritten to the frozen
        ``.stale`` alias (see :meth:`_async_snapshot_payloads`)."""
        return self._async_alias_out(s, self._last_site_outs[s])

    # --------------------------------------------- run-ahead pipeline (ISSUE 14)
    # Federation.RUN_AHEAD = d >= 1 decouples compute from the wire: the
    # reduce+relay tail of round r runs on the dedicated reducer worker
    # (:meth:`_ensure_reduce_pool`) while every site whose round-r payload
    # committed is immediately re-submitted — with the newest unconsumed
    # broadcast when one has been harvested, else (up to d deep) against
    # the last committed broadcast with the one-shot update keys stripped,
    # so no broadcast is ever applied twice.  The broadcast lag surfaces
    # as the site's wire_round echo lag, bounded by d; the aggregator's
    # window check accepts k + d and the reducer's gamma**lag discount
    # covers it (nodes/remote.py, parallel/reducer.py).  Barriers and any
    # non-steady round drain the pipeline and run the inline d=0 tail.

    def _pipeline_input(self, s):
        """The full input for site ``s`` when a broadcast it has not yet
        consumed is available (records the consumption and resets the
        run-ahead depth); None when the newest harvested broadcast was
        already delivered to this site."""
        cur = self.site_inputs.get(s) or {}
        stamp = cur.get(RemoteWire.ROUND.value)
        if stamp is not None and stamp == self._async_consumed.get(s):
            return None
        if stamp is not None:
            self._async_consumed[s] = stamp
        self._run_ahead_depth[s] = 0
        return self._site_input(s)

    def _run_ahead_eligible(self, inp):
        """True when the broadcast is a plain steady-state dSGD update the
        site may compute ahead of; multi-invocation sync protocols and
        run-level transitions block run-ahead (the engine waits on the
        reducer instead)."""
        return bool(inp.get(RemoteWire.UPDATE.value)) and not any(
            key in inp for key in _RUN_AHEAD_BLOCKERS
        )

    def _run_ahead_strip(self, inp):
        return {k: v for k, v in inp.items() if k not in _RUN_AHEAD_STRIP}

    def _reduce_job(self, rnd, site_outs, rec):
        """The reducer worker's unit of work: one round's whole wire tail.
        Samples whether site invocations were in flight while it ran (at
        entry AND at exit — the engine re-submits sites right after
        handing the job over, so the overlap usually begins mid-job) for
        the ``pipeline:reduce_concurrent`` telemetry counter."""
        pending = bool(self._async_pending)
        t0 = time.monotonic()
        out = self._remote_and_relay(rnd, site_outs, rec, record_outs=False)
        pending = pending or bool(self._async_pending)
        return out, time.monotonic() - t0, pending

    def _pipeline_submit_reduce(self, rnd, site_outs, rec):
        fut = self._ensure_reduce_pool().submit(
            self._reduce_job, rnd, dict(site_outs), rec
        )
        self._reduce_pending.append((rnd, fut, time.monotonic()))

    def _pipeline_harvest(self, rec, stall_site=None):
        """Harvest the OLDEST in-flight reduce (blocking if it has not
        finished), apply its broadcast to ``site_inputs``, and land the
        pipeline telemetry.  ``stall_site`` marks a forced harvest — a
        site exhausted its run-ahead horizon and the engine must block on
        the reducer worker (the ``pipeline:stall`` event the live plane's
        ``pipeline_stall`` verdict reads)."""
        if not self._reduce_pending:
            return None
        red_rnd, fut, _t_sub = self._reduce_pending.pop(0)
        blocked = not fut.done()
        t0 = time.monotonic()
        remote_out, dur, pending_at_start = fut.result()
        if blocked and stall_site is not None:
            rec.event(
                "pipeline:stall", cat="async", site=stall_site,
                reduce_round=red_rnd,
                waited_s=round(time.monotonic() - t0, 4),
                d=(self._async_cfg or {}).get("run_ahead", 0),
            )
        if pending_at_start and dur > 0:
            # seconds the reduce+relay tail ran while at least one site
            # invocation was in flight — the decoupling win, measurable
            rec.event(
                "pipeline:reduce_concurrent", cat="async",
                reduce_round=red_rnd, secs=round(dur, 4),
            )
        self.last_remote_out = remote_out
        self.site_inputs = {
            s: dict(remote_out) for s in self._alive_site_ids()
        }
        return remote_out

    def _pipeline_poll(self, rec):
        """Harvest every COMPLETED in-flight reduce, oldest first (non-
        blocking) — idle sites must only ever be handed the newest
        harvested broadcast."""
        out = None
        while self._reduce_pending and self._reduce_pending[0][1].done():
            out = self._pipeline_harvest(rec)
        return out

    def _pipeline_drain(self, rec, reason=None):
        """Block until every in-flight reduce has been harvested — the
        barrier contract: from here on the round runs the exact inline
        (d=0) schedule."""
        if not self._reduce_pending:
            return None
        n = len(self._reduce_pending)
        out = None
        while self._reduce_pending:
            out = self._pipeline_harvest(rec)
        if reason:
            rec.event("pipeline:drain", cat="async", reason=str(reason),
                      pending=n)
        return out

    def _pipeline_resubmit(self, rnd, s, rec, d):
        """Re-submit a site whose round-``rnd`` payload just committed:
        full input when an unconsumed broadcast exists, a depth-bounded
        run-ahead submission otherwise; depth exhaustion blocks on the
        reducer worker (stall) instead of running further ahead."""
        inp = self._pipeline_input(s)
        if inp is None:
            depth = self._run_ahead_depth.get(s, 0)
            base = self.site_inputs.get(s) or {}
            if depth >= d or not self._run_ahead_eligible(base):
                self._pipeline_harvest(rec, stall_site=s)
                inp = self._pipeline_input(s)
                if inp is None:
                    return  # no broadcast even after the harvest: stay idle
            else:
                self._run_ahead_depth[s] = depth + 1
                inp = self._run_ahead_strip(base)
                rec.event("async:run_ahead", cat="async", site=s,
                          depth=depth + 1, d=d)
        rec.metric(Metric.SITE_RUN_AHEAD,
                   float(self._run_ahead_depth.get(s, 0)), site=s)
        policy = self._invoke_policy(s)
        fut = self._async_pool.submit(
            self._async_attempt, policy, rnd + 1, s, inp, rec
        )
        self._async_pending[s] = (rnd + 1, fut, policy)

    def _pipeline_round(self, rnd, site_outs, rec, d):
        """The steady-state pipelined wire tail: freeze this round's fresh
        payloads behind their aliases, hand the reduce+relay to the
        reducer worker, then immediately re-submit every delivered site —
        compute for round ``rnd + 1`` overlaps the round-``rnd`` wire."""
        for s in sorted(site_outs):
            # a re-submitted site's next commit overwrites the live payload
            # names at an arbitrary moment while the deferred reduce reads
            # them — the reduce must consume the frozen generation-tagged
            # aliases instead.  EVERY delivered out is rewritten, not just
            # this round's fresh set: a chaos replay/stand-in redelivers
            # the last output, whose live names the site's next invocation
            # clobbers just the same (idempotent for already-aliased refs;
            # a no-op for sites with no snapshot yet)
            site_outs[s] = self._async_alias_out(s, site_outs[s])
        self._pipeline_submit_reduce(rnd, site_outs, rec)
        if _PIPELINE_FORCE_DRAIN:
            self._pipeline_drain(rec, reason="forced")
        self._pipeline_poll(rec)
        for s in sorted(self._async_fresh):
            if s in self.dead_sites or s in self._async_pending:
                continue
            self._pipeline_resubmit(rnd, s, rec, d)

    def _step_round_async(self, ac):
        """One engine round of the async mode: submit every idle site to
        the bounded pool, collect completed invocations, let in-window
        stragglers be represented by their last contribution, then run the
        shared remote+relay tail while the stragglers keep computing.
        With run-ahead configured (``ac['run_ahead'] >= 1``) the wire tail
        is pipelined instead (:meth:`_pipeline_round`)."""
        rec = self._recorder()
        rnd = self.rounds + 1
        rec.set_context(round=rnd)
        self._membership_round(rnd, rec)
        k, d = ac["k"], ac["run_ahead"]
        site_outs = {}
        self._async_fresh = set()
        with self.chaos.activate(rec), rec.span(
            "engine:round", cat="engine", mode="async"
        ):
            pool = self._ensure_async_pool(self._async_pool_size(ac))
            if d:
                # harvest completed reduces first: an idle site must never
                # be handed a broadcast it already consumed
                self._pipeline_poll(rec)
            # ---- submit: every alive site without a pending invocation
            # computes this round, against the latest broadcast
            for s in self._alive_site_ids():
                if s in self._async_pending:
                    continue
                replay = self._stale_replay(rnd, s, rec)
                if replay is not None:
                    site_outs[s] = replay
                    continue
                if d:
                    inp = self._pipeline_input(s)
                    if inp is None:
                        # the newest harvested broadcast was already
                        # consumed (a round that could not run ahead):
                        # the reducer worker is behind — block on it
                        self._pipeline_harvest(rec, stall_site=s)
                        inp = self._pipeline_input(s)
                    if inp is None:
                        inp = self._site_input(s)  # first rounds: no stamp
                else:
                    inp = self._site_input(s)
                policy = self._invoke_policy(s)
                fut = pool.submit(
                    self._async_attempt, policy, rnd, s, inp, rec
                )
                self._async_pending[s] = (rnd, fut, policy)

            # ---- collect: give THIS round's submissions the grace window
            # first (a healthy site's fresh contribution beats its
            # stand-in; a straggler's older pending would eat the full
            # timeout every round), then deliver what completed — the
            # completed phases/modes decide whether stand-ins are allowed.
            # The grace is ANCHORED at the round's fastest fresh
            # completion, not at collect entry: the peers define the
            # round's baseline, so a straggler is "this factor behind its
            # peers THIS round" regardless of how contention (the
            # pipelined reduce overlapping compute, a loaded host) shifts
            # everyone's absolute latency — an entry-anchored window
            # either expired before any healthy site landed (an all-
            # blocking round) or stretched until the straggler landed too
            # (the stand-in machinery silently disarmed)
            fresh_futs = [
                pend[1] for s in self._alive_site_ids()
                for pend in (self._async_pending.get(s),)
                if pend is not None and pend[0] == rnd
            ]
            if fresh_futs and not all(f.done() for f in fresh_futs):
                grace = self._async_grace()
                if grace:
                    from concurrent.futures import FIRST_COMPLETED
                    from concurrent.futures import wait as _futures_wait

                    _futures_wait(fresh_futs, return_when=FIRST_COMPLETED)
                    if not all(f.done() for f in fresh_futs):
                        _futures_wait(fresh_futs, timeout=grace)
            waiting = []
            for s in self._alive_site_ids():
                if s not in self._async_pending:
                    continue
                if self._async_pending[s][1].done():
                    self._async_deliver(rnd, s, rec, site_outs)
                else:
                    waiting.append(s)
            steady = self._async_steady(site_outs)
            for s in waiting:
                q = self._async_pending[s][0]
                # staleness of the stand-in = rounds since the straggler's
                # last FRESH contribution was submitted — exactly the lag
                # its wire_round echo shows the aggregator
                lag = rnd - self._async_last_sub.get(s, q)
                if k and steady and self._async_standin_ok(s) and lag <= k:
                    site_outs[s] = self._async_standin_out(s)
                    rec.event("async:stale", cat="async", site=s,
                              lag=lag, k=k)
                    rec.metric(Metric.SITE_STALENESS, float(lag), site=s)
                    continue
                if k and lag > k:
                    # the straggler fell past the window: the engine must
                    # block on it — the live plane's staleness_exceeded
                    # verdict reads this edge
                    rec.event("async:staleness_exceeded", cat="async",
                              site=s, lag=lag, k=k)
                    rec.metric(Metric.SITE_STALENESS, float(lag), site=s)
                self._async_deliver(rnd, s, rec, site_outs)

            # the pipeline decision re-judges steadiness over the COMPLETE
            # delivered set: ``steady`` above was computed on the fresh-
            # only outs to gate stand-ins, so a round where every site
            # merely missed the grace window (empty fresh set, no barrier
            # signal anywhere) would otherwise drain the pipeline into a
            # needless lockstep round
            pipelined = (
                bool(d) and bool(self._async_fresh)
                and self._async_steady(site_outs)
            )
            if pipelined:
                self._pipeline_round(rnd, site_outs, rec, d)
                remote_out = dict(self.last_remote_out)
            else:
                if d:
                    # any barrier/transition signal drains the pipeline:
                    # the round below runs the exact inline (d=0) tail
                    self._pipeline_drain(rec, reason="barrier")
                remote_out = self._remote_and_relay(rnd, site_outs, rec)
            self._finalize_leavers(site_outs, rec)
        rec.flush()
        if not pipelined:
            self.site_inputs = {
                s: dict(remote_out) for s in self._alive_site_ids()
            }
        self.rounds += 1
        return site_outs, remote_out

    def close(self):
        """Release engine resources: the async invocation pool (pending
        futures cancelled; running ones finish or fail on their own) and
        the run-ahead reducer worker (in-flight reduces abandoned).  The
        lockstep path never builds either, so this is a no-op there."""
        pool, self._async_pool = self._async_pool, None
        if pool is not None:
            for _q, fut, _p in self._async_pending.values():
                fut.cancel()
            pool.shutdown(wait=False, cancel_futures=True)
        self._async_pending = {}
        rpool, self._reduce_pool = self._reduce_pool, None
        if rpool is not None:
            for _rnd, fut, _t in self._reduce_pending:
                fut.cancel()
            rpool.shutdown(wait=False, cancel_futures=True)
        self._reduce_pending = []

    def run(self, max_rounds=100000, verbose=False):
        """Drive rounds until the aggregator reports SUCCESS."""
        while not self.success and self.rounds < max_rounds:
            _, remote_out = self.step_round()
            if verbose and logger.lazy_debug(self.rounds):
                logger.info(
                    f"round {self.rounds}: phase={remote_out.get('phase')} "
                    f"epoch={self.remote_cache.get('epoch')}",
                    True,
                )
        return self


class SubprocessEngine(InProcessEngine):
    """Protocol-faithful FRESH-PROCESS engine: every node invocation spawns
    ``python <script>`` with ``{"cache", "input", "state"}`` on stdin and
    reads ``{"output", "cache"}`` from stdout (the ``examples/*/local.py`` /
    ``remote.py`` contract) — no Python state can leak between rounds, which
    is what a real deployment whose engine containerizes each invocation
    looks like.  The engine round-trips each node's JSON-able cache (what
    the real engine persists); the live train state survives via
    ``cache['persist_round_state']`` (per-round on-disk state,
    ``nodes/local.py``) — without it, mid-run invocations fail loudly
    instead of silently re-initializing.

    ``first_input`` (per-site dict, or one dict broadcast to all) is merged
    into the first invocation's input so node args resolve through the
    3-tier pipeline exactly once (``ARGS_CACHED`` then rides the cache).
    """

    #: process-backed nodes: the pool threads only do process spawn + pipe
    #: I/O, so concurrent site invocations are real concurrency — no cap
    _ASYNC_POOL_CAP = None
    #: …and the reduce tail is a pipe/process request too, so the reducer
    #: worker genuinely overlaps site compute — run-ahead uncapped
    _RUN_AHEAD_CAP = None

    def __init__(self, workdir, n_sites, local_script, remote_script,
                 first_input=None, env=None, timeout=600, **kw):
        super().__init__(workdir, n_sites, **kw)
        # the in-process arg channels never reach a subprocess node — a
        # silently different configuration is worse than an error
        if self.args or self.site_args or self.site_spec:
            raise ValueError(
                "SubprocessEngine nodes run in their own processes: engine "
                "**args / site_args / inputspec are not shipped to them — "
                "pass node args via first_input (merged into the first "
                "invocation's input; the 3-tier arg pipeline caches them)"
            )
        self.local_script = str(local_script)
        self.remote_script = str(remote_script)
        self.env = env
        self.timeout = timeout
        if first_input is None:
            first_input = {}
        if not any(s in first_input for s in self.site_ids):
            first_input = {s: dict(first_input) for s in self.site_ids}
        self.first_input = first_input
        self._first_done = set()

    def _invoke(self, script, payload, target=None, rec=None, rnd=None):
        import json
        import subprocess
        import sys

        try:
            res = subprocess.run(
                [sys.executable, script],
                input=json.dumps(utils.clean_recursive(payload)),
                capture_output=True, text=True, env=self.env,
                timeout=self.timeout,
            )
        except subprocess.TimeoutExpired as exc:
            # a wedged node used to propagate as a raw TimeoutExpired with
            # no telemetry attribution and no stderr — map it to a typed
            # failure carrying the partial stderr the process managed to
            # write, and land an ``invoke:timeout`` event so `telemetry
            # doctor` can attribute the death (the retry/quorum machinery
            # in step_round treats it exactly like any other site failure)
            stderr = exc.stderr or ""
            if isinstance(stderr, bytes):
                stderr = stderr.decode("utf-8", "replace")
            if rec is not None:
                rec.event(
                    "invoke:timeout", cat="invoke", target=str(target),
                    timeout_s=float(self.timeout), script=str(script),
                    stderr=stderr[-1000:],
                )
            raise InvokeTimeout(
                f"{script} timed out after {self.timeout}s"
                f"{f' (target {target})' if target else ''}\n"
                f"--- partial stderr ---\n{stderr[-4000:]}"
            ) from exc
        if res.returncode != 0:
            raise RuntimeError(
                f"{script} exited rc={res.returncode}\n--- stderr ---\n"
                f"{res.stderr[-4000:]}"
            )
        # the node may print log lines; the LAST JSON line is the result
        for line in reversed(res.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except ValueError:
                    continue
        raise RuntimeError(
            f"{script} produced no JSON result\n--- stdout ---\n"
            f"{res.stdout[-2000:]}"
        )

    # --------------------------------------------------------- template hooks
    def _site_input(self, s):
        inp = dict(self.site_inputs[s])
        if s not in self._first_done:
            inp.update(self.first_input.get(s, {}))
            self._first_done.add(s)
        return self._membership_input(s, inp)

    def _site_attempt(self, rnd, s, inp, rec):
        # a hung process produces no output until the timeout kills it —
        # the chaos hang raises in its place.  Inside the span: a slow
        # fault's sleep is simulated compute; round pinned as a span attr
        # (a pool-thread invocation may outlive its submission round and
        # ambient context is only read at span end — see InProcessEngine)
        with rec.span(f"invoke:{s}", cat="invoke", round=rnd):
            self.chaos.invoke_fault(rnd, s, rec)
            res = self._invoke(self.local_script, {
                "cache": self.site_caches[s], "input": inp,
                "state": self.site_states[s],
            }, target=s, rec=rec, rnd=rnd)
        self.site_caches[s] = res.get("cache", {})
        return res["output"]

    def _remote_attempt(self, rnd, site_outs, rec):
        # fresh-process nodes load payloads OUTSIDE this process, so a
        # corrupt payload fails the whole invocation: the retry (which
        # first heals pending chaos damage) is the recovery.  Round pinned
        # as a span attr: the run-ahead reducer worker runs this one round
        # behind the engine's ambient round context.
        self.chaos.invoke_fault(rnd, "remote", rec)
        with rec.span("invoke:remote", cat="invoke", round=rnd):
            res = self._invoke(self.remote_script, {
                "cache": self.remote_cache, "input": site_outs,
                "state": self.remote_state,
            }, target="remote", rec=rec, rnd=rnd)
        self.remote_cache = res.get("cache", {})
        self.success = bool(res.get("success"))
        return res["output"]


class MeshEngine:
    """Full federated lifecycle with the mesh transport as the gradient plane.

    Host-side control mirrors :class:`~.nodes.COINNRemote`'s state machine —
    fold rotation, lockstep epochs, the validation cadence, exact cross-site
    count-merge of metrics, best-checkpoint saves, early stopping, per-fold
    test reduction, the global score CSV and the results zip (ref
    ``distrib/nodes/remote.py:238-287``) — while every training round is ONE
    compiled ``shard_map`` step over the ``(site, device)`` mesh
    (:class:`~.parallel.mesh.MeshFederation`) and evaluation is a compiled
    psum-reduced eval step over the same mesh.

    Semantics match :class:`InProcessEngine` byte-for-byte where the math is
    shared: same per-site data layout and splits, same loader order (seeded
    by ``(seed, epoch)``), same lockstep ``target_batches`` padding, same
    best/early-stop decisions, same score artifacts.  What differs is the
    wire: gradients never leave the devices.

    Pretrain broadcast is supported with the file transport's semantics
    (:meth:`_mesh_pretrain`): the max-train-data site trains locally for
    ``pretrain_args['epochs']``, and its best weights seed the replicated
    mesh state — exactly what the designated-site-pretrain + broadcast
    sequence produces on the engine transport.  Sparse test mode
    (``load_sparse`` — one dataset per test subject, per-subject
    ``save_predictions``) runs the fold test per-site on the host with the
    same exact count merge, like the engine transport's
    ``test_distributed``.  Metrics that are not jit-safe (AUC) fall back
    to per-site host evaluation with identical count/rank math.
    """

    def __init__(self, workdir, n_sites, trainer_cls=COINNTrainer,
                 dataset_cls=None, datahandle_cls=COINNDataHandle,
                 devices=None, devices_per_site=None, site_args=None, **args):
        self.workdir = str(workdir)
        self.n_sites = int(n_sites)
        self.trainer_cls = trainer_cls
        self.dataset_cls = dataset_cls
        self.datahandle_cls = datahandle_cls
        self.devices = devices
        self.devices_per_site = devices_per_site
        self.site_args = site_args or {}

        self.cache = dict(COINNLocal._ARG_DEFAULTS)
        self.cache.update(args)
        if self.cache.get("seed") is None:
            self.cache["seed"] = config.current_seed

        self.site_ids = [f"site_{i}" for i in range(self.n_sites)]
        self.site_states = {}
        for s in self.site_ids:
            base = os.path.join(self.workdir, s)
            outd = os.path.join(base, "out")
            for d in (base, outd):
                os.makedirs(d, exist_ok=True)
            self.site_states[s] = {
                "baseDirectory": base, "outputDirectory": outd, "clientId": s,
            }
        self.remote_out_dir = os.path.join(self.workdir, "remote_out")
        os.makedirs(self.remote_out_dir, exist_ok=True)
        self.site_caches = {}
        self.success = False
        self.results_zip = None
        self._trainer = None
        # sites excluded from every subsequent round (their train batches
        # and eval loaders degrade to fully-masked placeholders — the same
        # zero-participation path an empty-data site takes).  Empty here;
        # populated by subclasses with a dropout story (federation/engine).
        self.dead_sites = set()

    def _site_loads(self, s):
        """Whether site ``s`` gets a LIVE loader this epoch/eval (vs the
        fully-masked placeholder stream).  The elastic-membership subclass
        (federation/engine.py) overrides this with roster awareness: a
        retired or not-yet-admitted slot rides masked even when its data
        directory is populated."""
        return s not in self.dead_sites

    def site_data_dir(self, site_id, data_dir=None):
        d = os.path.join(
            self.site_states[site_id]["baseDirectory"],
            data_dir or self.cache.get("data_dir", "data"),
        )
        os.makedirs(d, exist_ok=True)
        return d

    # ------------------------------------------------------------- lifecycle
    def run(self):
        """Drive every fold to completion; sets ``success`` at the end."""
        handles = {}
        for s in self.site_ids:
            scache = dict(self.cache)
            scache.update(self.site_args.get(s, {}))
            self.site_caches[s] = scache
            h = self.datahandle_cls(
                cache=scache, state=self.site_states[s],
                dataset_cls=self.dataset_cls,
                dataloader_args=scache.get("dataloader_args"),
            )
            h.prepare_data()
            handles[s] = h
        rc = self.cache
        rc["num_folds"] = len(next(iter(self.site_caches.values()))["splits"])
        rc[Key.GLOBAL_TEST_SERIALIZABLE.value] = []
        # fold/epoch resume is honored only when a run-state record from an
        # interrupted run exists — per-fold checkpoints left behind by a
        # COMPLETED run (whose record _finish removed) never replay
        self._resuming = bool(rc.get("resume")) and os.path.exists(
            self._run_state_path()
        )
        done_folds = (
            self._load_run_state().get("completed_folds", {})
            if self._resuming else {}
        )
        self._write_run_state_marker()
        for fold in range(int(rc["num_folds"])):
            if str(fold) in done_folds:
                rc[Key.GLOBAL_TEST_SERIALIZABLE.value].append(done_folds[str(fold)])
                continue
            self._run_fold(str(fold), handles)
        self._finish()
        return self

    # ------------------------------------------------------- mid-run resume
    def _run_state_path(self):
        return os.path.join(self.workdir, ".mesh_resume.json")

    def _load_run_state(self):
        import json

        try:
            with open(self._run_state_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def _write_run_state(self, run_state):
        import json

        utils.atomic_write(self._run_state_path(), json.dumps(run_state))

    def _write_run_state_marker(self):
        """Fresh (non-resuming) runs RESET the record: a stale crashed-run
        record must never leak fold results into a later resume."""
        if not getattr(self, "_resuming", False) or not os.path.exists(
            self._run_state_path()
        ):
            self._write_run_state({"completed_folds": {}})

    def _record_fold_done(self, split_ix, payload):
        run_state = self._load_run_state()
        run_state.setdefault("completed_folds", {})[str(split_ix)] = payload
        self._write_run_state(run_state)

    def _epoch_autosave(self, trainer, fed, epoch):
        """Full mesh resume point at the epoch barrier: params/opt/rng +
        score logs + carried engine state (PowerSGD EF/Qs/warm-up counter).
        Cadence/opt-out via ``cache['autosave_epochs']`` (0 disables)."""
        rc = self.cache
        every = int(rc.get("autosave_epochs", 1) or 0)
        if every <= 0 or int(epoch) % every != 0:
            return
        extra = {
            "epoch": int(epoch),
            "train_log": rc[Key.TRAIN_LOG.value],
            "validation_log": rc[Key.VALIDATION_LOG.value],
            "best_val_epoch": rc.get("best_val_epoch", 0),
            "best_val_score": rc.get("best_val_score"),
            "fed": fed.serialize_comm_state(),
        }
        trainer.save_checkpoint(name=rc["latest_nn_state"], extra=extra)

    def _try_fold_resume(self, trainer, fed):
        """Restart the current fold from its latest epoch-barrier autosave.
        Returns the completed-epoch counter to continue from (0 = fresh).
        A corrupt/truncated autosave (crash mid-write) falls back to a
        fresh fold rather than wedging the resume path."""
        rc = self.cache
        path = trainer.checkpoint_path(rc["latest_nn_state"])
        if not (getattr(self, "_resuming", False) and os.path.exists(path)):
            return 0
        try:
            trainer.load_checkpoint(full_path=path)
        except Exception as exc:  # noqa: BLE001 — any decode failure
            # msgpack_restore raises before mutating the trainer, so the
            # fresh seeded init from _run_fold is still intact
            logger.warn(
                f"MeshEngine: unreadable autosave {path} ({exc}); "
                "restarting the fold fresh"
            )
            return 0
        extra = getattr(trainer, "last_checkpoint_extra", {})
        rc[Key.TRAIN_LOG.value] = [list(r) for r in extra.get("train_log", [])]
        rc[Key.VALIDATION_LOG.value] = [
            list(r) for r in extra.get("validation_log", [])
        ]
        rc["best_val_epoch"] = int(extra.get("best_val_epoch", 0))
        rc["best_val_score"] = extra.get("best_val_score")
        fed.restore_comm_state(dict(extra.get("fed", {})))
        epoch = int(extra.get("epoch", 0))
        logger.info(
            f"MeshEngine: resuming fold {rc['split_ix']} from epoch {epoch}",
            rc.get("verbose", True),
        )
        return epoch

    def _run_fold(self, split_ix, handles):
        rc = self.cache
        for s in self.site_ids:
            sc = self.site_caches[s]
            sc["split_ix"] = split_ix
            sc["split_file"] = sc["splits"][split_ix]
        log_dir = os.path.join(
            self.remote_out_dir, str(rc["task_id"]), f"fold_{split_ix}"
        )
        os.makedirs(log_dir, exist_ok=True)
        rc.update(log_dir=log_dir, split_ix=split_ix, epoch=0,
                  best_val_epoch=0, best_val_score=None)
        rc[Key.TRAIN_LOG.value] = []
        rc[Key.VALIDATION_LOG.value] = []
        rc[Key.TEST_METRICS.value] = []
        tag = f"{rc['task_id']}-{split_ix}"
        rc["best_nn_state"] = f"best.{tag}.ckpt"
        rc["latest_nn_state"] = f"latest.{tag}.ckpt"

        trainer = self.trainer_cls(
            cache=rc, input={},
            state={"outputDirectory": self.remote_out_dir}, data_handle=None,
        )
        trainer.init_nn()
        self._trainer = trainer
        self._mesh_pretrain(trainer, handles)
        fed = self._build_federation(rc)
        self._last_fed = fed
        self._run_fold_loop(split_ix, handles, trainer, fed, rc)

    def _build_federation(self, rc):
        """Construct this fold's federation transport — the hook the
        site-vectorized engine (:mod:`.federation.engine`) overrides to swap
        the per-rank mesh for the stacked-site vmap/shard_map plane while
        the whole lifecycle above stays shared."""
        from .parallel.mesh import MeshFederation

        trainer = self._trainer
        sp = int(rc.get("sequence_parallel", 1) or 1)
        tp = int(rc.get("tensor_parallel", 1) or 1)
        if sp > 1 and tp > 1:
            raise ValueError(
                f"sequence_parallel={sp} and tensor_parallel={tp} are "
                "mutually exclusive (one intra-site mesh axis); pick one"
            )
        if sp > 1:
            # intra-site axis shards the SEQUENCE (ring attention) instead
            # of the batch — the trainer must implement iteration_sharded
            if self.devices_per_site not in (None, sp):
                raise ValueError(
                    f"devices_per_site={self.devices_per_site} conflicts "
                    f"with sequence_parallel={sp}: the intra-site axis is "
                    "the sequence axis (sp ranks per site); drop one of the "
                    "two settings"
                )
            from .parallel.seq_mesh import SeqMeshFederation

            return SeqMeshFederation(
                trainer, self.n_sites, sp=sp,
                agg_engine=str(rc.get("agg_engine", "dSGD")),
                devices=self.devices,
            )
        if tp > 1:
            # intra-site axis shards the model's heavy matmuls (Megatron
            # col/row parallelism) — the trainer must implement iteration_tp
            if self.devices_per_site not in (None, tp):
                raise ValueError(
                    f"devices_per_site={self.devices_per_site} conflicts "
                    f"with tensor_parallel={tp}: the intra-site axis is the "
                    "tensor axis (tp ranks per site); drop one of the two "
                    "settings"
                )
            from .parallel.tp_mesh import TPMeshFederation

            return TPMeshFederation(
                trainer, self.n_sites, tp=tp,
                agg_engine=str(rc.get("agg_engine", "dSGD")),
                devices=self.devices,
            )
        return MeshFederation(
            trainer, self.n_sites,
            agg_engine=str(rc.get("agg_engine", "dSGD")),
            devices=self.devices, devices_per_site=self.devices_per_site,
        )

    def _recorder(self):
        """Engine-lane recorder (``telemetry.engine.jsonl`` in the
        workdir), enabled by the same ``profile``/``telemetry`` flags as
        the node-side recorders.  The base mesh engine records no per-site
        invocation spans, but capture events (``capture:profile``/
        ``capture:failed`` from the anomaly-triggered profiler wrap in
        ``_run_fold_loop``) must land on a REAL lane — a null recorder
        here would silently drop the postmortem's capture links."""
        return _engine_recorder(self, [self.cache, *self.site_args.values()])

    def _round_hook(self, site_batches):
        """Per-round boundary before the compiled federated step — the hook
        subclasses with a per-site dropout/chaos story override (the
        site-vectorized engine injects invoke faults and masks dead sites
        here).  Default: pass-through."""
        return site_batches

    def _run_fold_loop(self, split_ix, handles, trainer, fed, rc):
        log_dir = rc["log_dir"]
        bs = int(rc.get("batch_size", 16))
        train_sets = {s: handles[s].get_train_dataset() for s in self.site_ids}
        if not any(len(ds) for ds in train_sets.values()):
            raise ValueError(
                f"fold {split_ix}: every site's train dataset is empty"
            )
        # lockstep epochs: every site pads to the global max batches/epoch
        # (≙ remote's target_batches broadcast)
        target_batches = max(
            (math.ceil(len(ds) / bs) for ds in train_sets.values() if len(ds)),
            default=1,
        )
        k = max(int(rc.get("local_iterations", 1)), 1)
        epochs = int(rc.get("epochs", 1))
        val_every = max(int(rc.get("validation_epochs", 1)), 1)
        ep_averages, ep_metrics = trainer.new_averages(), trainer.new_metrics()
        epoch = self._try_fold_resume(trainer, fed)
        # the resume point may already satisfy the stop condition (crash
        # after the last barrier but before the fold test finished)
        fold_complete = epoch >= epochs or (epoch > 0 and stop_training_(epoch, rc))
        while not fold_complete:
            epoch += 1
            rc["epoch"] = epoch
            # loader epoch is 0-based (matches the cursor transport's
            # cache['epoch'] at first use); a site with no train data gets a
            # fully-masked placeholder stream (mirrors _mesh_eval) so its
            # rank participates in the lockstep step contributing nothing
            iters = [
                (iter(handles[s].get_loader(
                    "train", dataset=train_sets[s], shuffle=True,
                    seed=int(rc.get("seed", 0)), epoch=epoch - 1,
                    target_batches=target_batches,
                )) if len(train_sets[s]) and self._site_loads(s)
                 else None)
                for s in self.site_ids
            ]
            done = 0
            while done < target_batches:
                take = min(k, target_batches - done)
                site_batches = [
                    ([next(it) for _ in range(take)] if it is not None else None)
                    for it in iters
                ]
                template = next(b for b in site_batches if b is not None)
                for i, b in enumerate(site_batches):
                    if b is None:
                        site_batches[i] = [
                            {**tb, "_mask": np.zeros_like(np.asarray(tb["_mask"]))}
                            for tb in template
                        ]
                # an anomaly-armed deep capture (telemetry/capture.py)
                # wraps the whole compiled federated round; no-op (one
                # dict lookup) unless a watchdog detector armed it
                with _capture.captured_round(
                    rc, self.remote_out_dir, self._recorder()
                ):
                    aux = fed.train_step(self._round_hook(site_batches))
                trainer.fold_train_outputs(aux, ep_averages, ep_metrics)
                done += take
            if epoch % val_every != 0:
                # no stop check off the validation cadence: the file-transport
                # remote evaluates the epoch limit only at the validation
                # barrier (remote.py _next_epoch), so with epochs % val_every
                # != 0 both transports train up to the next validation epoch
                continue
            # ---- epoch barrier (≙ remote VALIDATION_WAITING → TRAIN_WAITING)
            rc[Key.TRAIN_LOG.value].append([*ep_averages.get(), *ep_metrics.get()])
            ep_averages, ep_metrics = trainer.new_averages(), trainer.new_metrics()
            v_avg, v_met = self._mesh_eval(fed, handles, "validation")
            rc[Key.VALIDATION_LOG.value].append([*v_avg.get(), *v_met.get()])
            # no fallback: a missing monitor metric must fail loudly, exactly
            # like the file-transport remote (``remote.py`` ``_save_if_better``)
            score = v_met.extract(rc.get("monitor_metric", "f1"))
            if performance_improved_(epoch, score, rc):
                trainer.save_checkpoint(name=rc["best_nn_state"])
            if logger.lazy_debug(epoch):
                plotter.plot_progress(
                    rc, log_dir,
                    plot_keys=[Key.TRAIN_LOG.value, Key.VALIDATION_LOG.value],
                    epoch=epoch,
                )
            self._epoch_autosave(trainer, fed, epoch)
            if epoch >= epochs or stop_training_(epoch, rc):
                break

        # ---- fold test with the best params (≙ test_distributed + on_run_end)
        if os.path.exists(trainer.checkpoint_path(rc["best_nn_state"])):
            trainer.load_checkpoint(name=rc["best_nn_state"])
        t_avg, t_met = self._mesh_eval(fed, handles, "test")
        rc[Key.TEST_METRICS.value].append([*t_avg.get(), *t_met.get()])
        fold_payload = {"averages": t_avg.serialize(), "metrics": t_met.serialize()}
        rc[Key.GLOBAL_TEST_SERIALIZABLE.value].append(fold_payload)
        self._record_fold_done(split_ix, utils.clean_recursive(fold_payload))
        plotter.plot_progress(
            rc, log_dir, plot_keys=[Key.TRAIN_LOG.value, Key.VALIDATION_LOG.value],
            epoch=rc.get("epoch"),
        )
        utils.save_scores(rc, log_dir=log_dir, file_keys=[Key.TEST_METRICS.value])
        utils.save_cache(rc, {"outputDirectory": log_dir})

    # --------------------------------------------------------------- pretrain
    def _mesh_pretrain(self, trainer, handles):
        """Designated-site pretrain with the engine transport's semantics
        (ref ``distrib/nodes/local.py:152-170``, ``remote.py:205-215``):
        the max-train-data site trains locally for
        ``pretrain_args['epochs']`` (its best weights land in a transfer
        dir, exactly like ``COINNTrainer._save_if_better``), then the
        replicated mesh state is rebuilt from a FRESH init + those weights
        (params/step/rng from the checkpoint, fresh optimizer) — the same
        state every file-transport site holds after the PRE_COMPUTATION
        broadcast load."""
        rc = self.cache
        p_args = dict(rc.get("pretrain_args") or {})
        if int(p_args.get("epochs", 0) or 0) <= 0:
            return False
        sizes = {s: len(handles[s].get_train_dataset()) for s in self.site_ids}
        designated = max(sizes, key=sizes.get)
        xfer = os.path.join(self.workdir, "pretrain_xfer")
        os.makedirs(xfer, exist_ok=True)

        # overlay pretrain_args; shield the fold's logs/early-stop state,
        # the resume flag (a fold resume must never short-circuit pretrain
        # or let it re-load a federated autosave), and the checkpoint names
        # (train_local's _on_train_end autosaves unconditionally — writing
        # the FOLD's latest ckpt here would corrupt crash resume with
        # pretrain-site history and wipe the 'fed' engine state)
        shield = set(p_args) | {
            "pretrain", "weights_file", "autosave_epochs", "resume",
            "latest_nn_state", "best_nn_state",
            Key.TRAIN_LOG.value, Key.VALIDATION_LOG.value,
            "best_val_epoch", "best_val_score", "epoch", "cursor",
        }
        saved = {k: rc.get(k) for k in shield}
        rc.update(p_args)
        rc.update(pretrain=True, weights_file=None, autosave_epochs=0,
                  resume=False,
                  latest_nn_state=f"pretrain.latest.{rc['task_id']}.ckpt",
                  best_nn_state=f"pretrain.best.{rc['task_id']}.ckpt")
        rc[Key.TRAIN_LOG.value] = []
        rc[Key.VALIDATION_LOG.value] = []
        rc.update(best_val_epoch=0, best_val_score=None)
        old_state, old_handle = trainer.state, trainer.data_handle
        trainer.state = dict(old_state, transferDirectory=xfer)
        trainer.data_handle = handles[designated]
        try:
            trainer.train_local(
                handles[designated].get_train_dataset(),
                handles[designated].get_validation_dataset(),
            )
        finally:
            trainer.state, trainer.data_handle = old_state, old_handle
            wfile = rc.get("weights_file")
            for k, v in saved.items():
                if v is None:
                    # absent before pretrain (or legitimately None): remove
                    # rather than leave a None that defeats `.get(k, default)`
                    rc.pop(k, None)
                else:
                    rc[k] = v
        # broadcast-equivalent adoption: every site = fresh init + weights
        trainer.init_nn()
        if wfile and os.path.exists(os.path.join(xfer, wfile)):
            trainer.load_checkpoint(
                full_path=os.path.join(xfer, wfile), load_optimizer=False,
                allow_torch=False,  # broadcast file: framework msgpack only
            )
        logger.info(
            f"MeshEngine: pretrain at {designated} "
            f"({'adopted ' + wfile if wfile else 'no improvement'})",
            rc.get("verbose", True),
        )
        return True

    # ------------------------------------------------------------- evaluation
    def _mesh_eval(self, fed, handles, which):
        """Globally-reduced evaluation: per-site loaders padded to lockstep
        length, one psum-reduced compiled step per batch index."""
        trainer = self._trainer
        if which == "test" and bool(self.cache.get("load_sparse")):
            # sparse test: one dataset per subject so save_predictions can
            # dump per-subject outputs — host path, exact count merge (≙
            # the engine transport's test_distributed)
            return self._host_test_sparse(handles)
        # non-jit-safe metrics (AUC) also run on the mesh: the compiled
        # step gathers (score, true, mask) across sites and the host
        # accumulates — no serial per-site fallback (round-4 perf cliff)
        bs = int(self.cache.get("batch_size", 16))
        datasets = {
            s: (handles[s].get_validation_dataset() if which == "validation"
                else handles[s].get_test_dataset())
            for s in self.site_ids
        }
        nb = max(
            (math.ceil(len(ds) / bs) for ds in datasets.values() if len(ds)),
            default=0,
        )
        metrics, averages = trainer.new_metrics(), trainer.new_averages()
        if nb == 0:
            return averages, metrics
        loaders = {
            s: (iter(handles[s].get_loader(
                which, dataset=datasets[s], shuffle=False, target_batches=nb))
                if len(datasets[s]) and self._site_loads(s) else None)
            for s in self.site_ids
        }
        for _ in range(nb):
            batches = [
                (next(loaders[s]) if loaders[s] is not None else None)
                for s in self.site_ids
            ]
            template = next(b for b in batches if b is not None)
            filled = []
            for b in batches:
                if b is None:  # site with no data: fully-masked placeholder
                    b = dict(template)
                    b["_mask"] = np.zeros_like(np.asarray(template["_mask"]))
                filled.append(b)
            m_state, a_state, hs = fed.eval_step(filled)
            if m_state is not None:
                metrics.update(m_state)
            elif hs is not None:
                metrics.add(
                    np.asarray(hs["score"]), np.asarray(hs["true"]),
                    mask=np.asarray(hs["mask"]),
                )
            elif not metrics.jit_safe:
                # non-jit-safe metrics with an iteration that exposes no
                # pred/true (host_scores_payload returned None): the mesh
                # path cannot feed them — fall back to the exact per-site
                # host evaluation rather than return silently-empty metrics
                return self._host_eval(handles, which)
            averages.update(a_state)
        return averages, metrics

    def _host_test_sparse(self, handles):
        """Fold test over per-subject datasets (``load_sparse``), per site
        on the host, with per-subject ``save_predictions`` when asked."""
        return self._host_eval(
            handles, "test",
            datasets_fn=lambda h: h.get_test_dataset(load_sparse=True),
            save_pred=bool(self.cache.get("save_predictions")),
        )

    def _host_eval(self, handles, which, datasets_fn=None, save_pred=False):
        """Per-site host-side evaluation with exact cross-site accumulation —
        the fallback for metrics whose state is not jit-safe (AUC) and the
        sparse-test path.  ``datasets_fn(handle)`` overrides the default
        dataset lookup (may return a LIST of datasets)."""
        trainer = self._trainer
        metrics, averages = trainer.new_metrics(), trainer.new_averages()
        mode = Mode.VALIDATION if which == "validation" else Mode.TEST
        if datasets_fn is None:
            datasets_fn = (
                (lambda h: h.get_validation_dataset())
                if which == "validation" else (lambda h: h.get_test_dataset())
            )
        shared_state = trainer.state
        try:
            for s in self.site_ids:
                trainer.data_handle = handles[s]
                # per-site state during the site's eval: user hooks
                # (save_predictions) see the SAME clientId/baseDirectory/
                # outputDirectory the engine transport would give them, and
                # per-subject dumps land in the site's own output dir
                trainer.state = self.site_states[s]
                ds = datasets_fn(handles[s])
                ds = ds if isinstance(ds, list) else [ds]
                if not any(len(d) for d in ds):
                    continue
                a, m = trainer.evaluation(mode, ds, save_pred=save_pred)
                metrics.accumulate(m)
                averages.accumulate(a)
        finally:
            trainer.data_handle = None
            trainer.state = shared_state
        return averages, metrics

    # ---------------------------------------------------------------- wrap-up
    def _finish(self):
        """All folds done: reduce fold scores, write the CSV, zip results
        (≙ remote ``_send_global_scores``)."""
        trainer = self._trainer
        if trainer is None:
            # every fold was replayed from the run-state record (resume after
            # a crash inside _finish): metric shells need no initialized nn
            trainer = self.trainer_cls(
                cache=self.cache, input={},
                state={"outputDirectory": self.remote_out_dir}, data_handle=None,
            )
        rc = self.cache
        pairs = rc[Key.GLOBAL_TEST_SERIALIZABLE.value]
        averages = trainer.new_averages().reduce_sites(
            [p["averages"] for p in pairs]
        )
        metrics = trainer.new_metrics().reduce_sites(
            [p["metrics"] for p in pairs]
        )
        rc["global_test_metrics"] = [[*averages.get(), *metrics.get()]]
        task_dir = os.path.join(self.remote_out_dir, str(rc["task_id"]))
        utils.save_scores(rc, log_dir=task_dir, file_keys=["global_test_metrics"])
        stamp = "_".join(str(datetime.datetime.now()).split(" "))
        zip_name = f"{rc['task_id']}_{rc.get('agg_engine')}_{stamp}"
        shutil.make_archive(os.path.join(self.workdir, zip_name), "zip", task_dir)
        self.results_zip = f"{zip_name}.zip"
        # the run completed: clear the resume record so a LATER run in the
        # same workdir can never silently replay this run's fold results
        try:
            os.remove(self._run_state_path())
        except OSError:
            pass
        self.success = True


class SiteRunner:
    """Single-site, no-engine debug harness (≙ ref ``SiteRunner``): drives a
    site through INIT_RUNS then NEXT_RUN with ``pretrain=True`` so the full
    local training loop runs without any aggregator.

    Drop-in compatibility with COINSTAC computation specs (ref
    ``site_runner.py:8-26``): pass ``inputspec`` (an ``inputspec.json`` path
    or the simulator data dir holding one) + ``site_index`` and the spec's
    ``{key: {"value": ...}}`` entries become the run's args; the directory
    layout matches the simulator's ``input/local{i}/simulatorRun``.
    """

    def __init__(self, workdir, task_id="task", site_id=None, inputspec=None,
                 site_index=0, **args):
        self.workdir = str(workdir)
        if site_id is None:
            site_id = f"local{int(site_index)}"
        if inputspec is not None:
            spec_args = load_inputspec(inputspec, site_index=site_index)
            args = {**spec_args, **args}  # explicit kwargs win
        base = os.path.join(self.workdir, "input", site_id, "simulatorRun")
        outd = os.path.join(self.workdir, "output", site_id)
        xfer = os.path.join(self.workdir, "transfer", site_id)
        for d in (base, outd, xfer):
            os.makedirs(d, exist_ok=True)
        self.state = {
            "baseDirectory": base,
            "outputDirectory": outd,
            "transferDirectory": xfer,
            "clientId": site_id,
        }
        args.setdefault("task_id", task_id)
        args.setdefault("pretrain_args", {"epochs": args.get("epochs", 10)})
        self.args = args
        self.cache = {}

    @property
    def data_dir(self):
        d = os.path.join(self.state["baseDirectory"], self.args.get("data_dir", "data"))
        os.makedirs(d, exist_ok=True)
        return d

    def run(self, trainer_cls, dataset_cls=None, datahandle_cls=COINNDataHandle):
        node = COINNLocal(cache=self.cache, input={}, state=self.state, **self.args)
        node(trainer_cls=trainer_cls, dataset_cls=dataset_cls,
             datahandle_cls=datahandle_cls)

        seed = self.cache.get("seed", 0)
        nxt = {
            "phase": Phase.NEXT_RUN.value,
            "global_runs": {
                self.state["clientId"]: {
                    "split_ix": "0", "seed": seed, "pretrain": True,
                }
            },
        }
        node = COINNLocal(cache=self.cache, input=nxt, state=self.state, **self.args)
        out = node(trainer_cls=trainer_cls, dataset_cls=dataset_cls,
                   datahandle_cls=datahandle_cls)
        return out["output"]
