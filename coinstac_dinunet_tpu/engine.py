"""In-process federation engine (simulator) + single-site runner.

The reference has **no network code**: an external COINSTAC engine (Node.js)
invokes each node with ``cache``/``input``/``state`` dicts and relays each
node's ``output`` JSON plus dropped transfer files (SURVEY.md §0).
:class:`InProcessEngine` reproduces that contract in one Python process — it
is the multi-node test backbone (SURVEY §4 "golden-file protocol tests" gap)
and the engine-transport benchmark driver.  :class:`SiteRunner` is the
single-site no-engine debug harness (≙ ref ``site_runner.py:8-45``).

Directory layout per site ``i`` under ``workdir``::

    site_<i>/            baseDirectory   (site's private data + inbox)
    site_<i>/out         outputDirectory
    remote_base/site_<i> site's transferDirectory == aggregator's inbox
    remote_xfer          aggregator's transferDirectory (broadcast outbox)
"""
import os
import shutil

from .config.keys import Mode, Phase
from .data import COINNDataHandle
from .nodes import COINNLocal, COINNRemote
from .trainer import COINNTrainer
from .utils import logger


class InProcessEngine:
    """Runs N site nodes + one aggregator, relaying outputs and files."""

    def __init__(self, workdir, n_sites, trainer_cls=COINNTrainer,
                 dataset_cls=None, datahandle_cls=COINNDataHandle,
                 remote_trainer_cls=None, learner_cls=None, reducer_cls=None,
                 site_args=None, **args):
        self.workdir = str(workdir)
        self.n_sites = int(n_sites)
        self.trainer_cls = trainer_cls
        self.remote_trainer_cls = remote_trainer_cls or trainer_cls
        self.dataset_cls = dataset_cls
        self.datahandle_cls = datahandle_cls
        self.learner_cls = learner_cls
        self.reducer_cls = reducer_cls
        self.args = args
        self.site_args = site_args or {}

        self.site_ids = [f"site_{i}" for i in range(self.n_sites)]
        self.site_caches = {s: {} for s in self.site_ids}
        self.remote_cache = {}
        self.site_states = {}
        for s in self.site_ids:
            base = os.path.join(self.workdir, s)
            xfer = os.path.join(self.workdir, "remote_base", s)
            outd = os.path.join(base, "out")
            for d in (base, xfer, outd):
                os.makedirs(d, exist_ok=True)
            self.site_states[s] = {
                "baseDirectory": base,
                "outputDirectory": outd,
                "transferDirectory": xfer,
                "clientId": s,
            }
        self.remote_state = {
            "baseDirectory": os.path.join(self.workdir, "remote_base"),
            "transferDirectory": os.path.join(self.workdir, "remote_xfer"),
            "outputDirectory": os.path.join(self.workdir, "remote_out"),
        }
        for d in self.remote_state.values():
            os.makedirs(d, exist_ok=True)

        self.site_inputs = {s: {} for s in self.site_ids}
        self.rounds = 0
        self.success = False
        self.last_remote_out = {}

    def site_data_dir(self, site_id, data_dir="data"):
        d = os.path.join(self.site_states[site_id]["baseDirectory"], data_dir)
        os.makedirs(d, exist_ok=True)
        return d

    # ------------------------------------------------------------- one round
    def step_round(self):
        """One full engine round: every site computes, files relay to the
        aggregator, the aggregator computes, its output + files relay back."""
        site_outs = {}
        for s in self.site_ids:
            node = COINNLocal(
                cache=self.site_caches[s],
                input=self.site_inputs[s],
                state=self.site_states[s],
                **{**self.args, **self.site_args.get(s, {})},
            )
            result = node(
                trainer_cls=self.trainer_cls,
                dataset_cls=self.dataset_cls,
                datahandle_cls=self.datahandle_cls,
                learner_cls=self.learner_cls,
            )
            site_outs[s] = result["output"]

        remote = COINNRemote(
            cache=self.remote_cache, input=site_outs, state=self.remote_state
        )
        result = remote(
            trainer_cls=self.remote_trainer_cls, reducer_cls=self.reducer_cls
        )
        remote_out = result["output"]
        self.success = bool(result.get("success"))
        self.last_remote_out = remote_out

        # relay aggregator transfer files into every site's inbox
        xfer = self.remote_state["transferDirectory"]
        for f in os.listdir(xfer):
            for s in self.site_ids:
                shutil.copy(
                    os.path.join(xfer, f),
                    os.path.join(self.site_states[s]["baseDirectory"], f),
                )
        self.site_inputs = {s: dict(remote_out) for s in self.site_ids}
        self.rounds += 1
        return site_outs, remote_out

    def run(self, max_rounds=100000, verbose=False):
        """Drive rounds until the aggregator reports SUCCESS."""
        while not self.success and self.rounds < max_rounds:
            _, remote_out = self.step_round()
            if verbose and logger.lazy_debug(self.rounds):
                logger.info(
                    f"round {self.rounds}: phase={remote_out.get('phase')} "
                    f"epoch={self.remote_cache.get('epoch')}",
                    True,
                )
        return self


class SiteRunner:
    """Single-site, no-engine debug harness (≙ ref ``SiteRunner``): drives a
    site through INIT_RUNS then NEXT_RUN with ``pretrain=True`` so the full
    local training loop runs without any aggregator."""

    def __init__(self, workdir, task_id="task", site_id="local0", **args):
        self.workdir = str(workdir)
        base = os.path.join(self.workdir, "input", site_id, "simulatorRun")
        outd = os.path.join(self.workdir, "output", site_id)
        xfer = os.path.join(self.workdir, "transfer", site_id)
        for d in (base, outd, xfer):
            os.makedirs(d, exist_ok=True)
        self.state = {
            "baseDirectory": base,
            "outputDirectory": outd,
            "transferDirectory": xfer,
            "clientId": site_id,
        }
        args.setdefault("task_id", task_id)
        args.setdefault("pretrain_args", {"epochs": args.get("epochs", 10)})
        self.args = args
        self.cache = {}

    @property
    def data_dir(self):
        d = os.path.join(self.state["baseDirectory"], self.args.get("data_dir", "data"))
        os.makedirs(d, exist_ok=True)
        return d

    def run(self, trainer_cls, dataset_cls=None, datahandle_cls=COINNDataHandle):
        node = COINNLocal(cache=self.cache, input={}, state=self.state, **self.args)
        node(trainer_cls=trainer_cls, dataset_cls=dataset_cls,
             datahandle_cls=datahandle_cls)

        seed = self.cache.get("seed", 0)
        nxt = {
            "phase": Phase.NEXT_RUN.value,
            "global_runs": {
                self.state["clientId"]: {
                    "split_ix": "0", "seed": seed, "pretrain": True,
                }
            },
        }
        node = COINNLocal(cache=self.cache, input=nxt, state=self.state, **self.args)
        out = node(trainer_cls=trainer_cls, dataset_cls=dataset_cls,
                   datahandle_cls=datahandle_cls)
        return out["output"]
