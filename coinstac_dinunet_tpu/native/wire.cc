// coinnwire — native runtime for the tensor-wire transport.
//
// The reference's runtime-adjacent native work all lives in its dependencies
// (torch/numpy/OpenCV; the repo itself is pure Python — SURVEY.md §2).  This
// framework keeps the same split for *compute* (XLA/Pallas kernels) but
// implements the *transport* runtime natively: the engine transport moves
// multi-hundred-MB gradient payloads per round through the filesystem
// (≙ ref utils/tensorutils.py:50-55 np.save/np.load), and the aggregator
// loads N site payloads concurrently (≙ ref distrib/reducer.py:18-23
// multiprocessing pool).  Here that is:
//
//   - coinn_pack_file: single-syscall-friendly gather-write of
//     [magic | manifest-len | manifest | raw buffers] with no intermediate
//     join-copy of the payload.
//   - coinn_load_file / coinn_load_many: posix_fadvise(SEQUENTIAL) bulk
//     reads, fanned out on std::thread for the many-site case — true
//     parallelism with no GIL and no process pool (the reference forks a
//     multiprocessing pool per aggregator call).
//   - a 64-bit payload checksum (coinn_checksum), exposed for transports
//     that want to verify payloads; the wire format itself does not embed
//     it (the filesystem hop is assumed reliable, as in the reference).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this environment).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <sys/uio.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------- checksum
// 64-bit mix-based rolling checksum (wyhash-style multiply-fold; not crypto).
uint64_t coinn_checksum(const uint8_t* buf, uint64_t len) {
  const uint64_t k0 = 0x9e3779b97f4a7c15ull, k1 = 0xbf58476d1ce4e5b9ull;
  uint64_t h = len * k0;
  uint64_t i = 0;
  for (; i + 8 <= len; i += 8) {
    uint64_t w;
    std::memcpy(&w, buf + i, 8);
    h = (h ^ w) * k1;
    h ^= h >> 29;
  }
  uint64_t tail = 0;
  for (uint64_t j = 0; i + j < len; ++j) tail |= uint64_t(buf[i + j]) << (8 * j);
  h = (h ^ tail) * k0;
  h ^= h >> 32;
  return h;
}

// ------------------------------------------------------------------- write
// Gather-write n_bufs buffers after a header; returns 0 on success, -errno.
int coinn_pack_file(const char* path, const uint8_t* header, uint64_t header_len,
                    const uint8_t** bufs, const uint64_t* sizes, int32_t n_bufs) {
  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -1;
  std::vector<iovec> iov;
  iov.reserve(size_t(n_bufs) + 1);
  iov.push_back({const_cast<uint8_t*>(header), size_t(header_len)});
  for (int32_t i = 0; i < n_bufs; ++i)
    iov.push_back({const_cast<uint8_t*>(bufs[i]), size_t(sizes[i])});
  // writev caps at IOV_MAX entries; loop over chunks, resuming partial writes
  size_t idx = 0;
  while (idx < iov.size()) {
    size_t n = std::min(iov.size() - idx, size_t(512));
    ssize_t wrote = ::writev(fd, iov.data() + idx, int(n));
    if (wrote < 0) {
      ::close(fd);
      return -2;
    }
    size_t w = size_t(wrote);
    while (idx < iov.size() && w >= iov[idx].iov_len) {
      w -= iov[idx].iov_len;
      ++idx;
    }
    if (idx < iov.size() && w > 0) {
      iov[idx].iov_base = static_cast<uint8_t*>(iov[idx].iov_base) + w;
      iov[idx].iov_len -= w;
    }
  }
  ::close(fd);
  return 0;
}

// -------------------------------------------------------------------- read
// Reads the whole file into a malloc'd buffer. Returns size, 0 on failure.
// Caller frees with coinn_free.
uint64_t coinn_load_file(const char* path, uint8_t** out) {
  *out = nullptr;
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return 0;
  struct stat st;
  if (::fstat(fd, &st) != 0 || st.st_size < 0) {
    ::close(fd);
    return 0;
  }
#ifdef POSIX_FADV_SEQUENTIAL
  ::posix_fadvise(fd, 0, 0, POSIX_FADV_SEQUENTIAL);
#endif
  uint64_t size = uint64_t(st.st_size);
  if (size == 0) {  // empty file: success, no buffer
    ::close(fd);
    return 0;
  }
  uint8_t* buf = static_cast<uint8_t*>(std::malloc(size));
  if (!buf) {
    ::close(fd);
    return 0;
  }
  uint64_t off = 0;
  while (off < size) {
    ssize_t got = ::read(fd, buf + off, size - off);
    if (got <= 0) {
      std::free(buf);
      ::close(fd);
      return 0;
    }
    off += uint64_t(got);
  }
  ::close(fd);
  *out = buf;
  return size;
}

// Load n files concurrently (one thread per file, capped at hw threads).
// outs[i]/sizes[i] receive each file's buffer; sizes[i]==0 marks failure.
void coinn_load_many(const char** paths, int32_t n, uint8_t** outs,
                     uint64_t* sizes) {
  int32_t cap = int32_t(std::thread::hardware_concurrency());
  if (cap < 1) cap = 1;
  std::vector<std::thread> pool;
  for (int32_t start = 0; start < n; start += cap) {
    int32_t end = std::min(n, start + cap);
    pool.clear();
    for (int32_t i = start; i < end; ++i)
      pool.emplace_back([&, i] { sizes[i] = coinn_load_file(paths[i], &outs[i]); });
    for (auto& t : pool) t.join();
  }
}

void coinn_free(uint8_t* buf) { std::free(buf); }

int32_t coinn_abi_version() { return 1; }

}  // extern "C"
