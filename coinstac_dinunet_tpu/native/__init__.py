"""ctypes bindings for the native wire runtime (``wire.cc``).

Builds ``libcoinnwire.so`` with g++ on first import (cached beside the
source; rebuilt when ``wire.cc`` changes), exposes

- :func:`pack_file` — gather-write a header + list of buffers with zero
  payload joins,
- :func:`load_file` / :func:`load_many` — bulk (and GIL-free parallel)
  payload reads,
- :func:`checksum` — 64-bit payload integrity hash,

and degrades cleanly: :func:`available` is False when no compiler or load
fails, and callers (``utils/tensorutils``, ``parallel/reducer``) fall back to
the pure-Python path.  Set ``COINN_NATIVE=0`` to force the fallback.
"""
import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "wire.cc")
_LIB = os.path.join(_DIR, "libcoinnwire.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build():
    # build to a process-unique temp name then rename: atomic against
    # concurrent builders (multi-node processes, pytest workers) and never
    # overwrites a .so another live process has mapped
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        _SRC, "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("COINN_NATIVE", "1") == "0":
            return None
        try:
            if (not os.path.exists(_LIB)
                    or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_LIB)
            lib.coinn_abi_version.restype = ctypes.c_int32
            if lib.coinn_abi_version() != 1:
                return None
            lib.coinn_checksum.restype = ctypes.c_uint64
            lib.coinn_checksum.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
            lib.coinn_pack_file.restype = ctypes.c_int32
            lib.coinn_pack_file.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_int32,
            ]
            lib.coinn_load_file.restype = ctypes.c_uint64
            lib.coinn_load_file.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ]
            lib.coinn_load_many.restype = None
            lib.coinn_load_many.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_int32,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.coinn_free.argtypes = [ctypes.c_void_p]
            _lib = lib
        except Exception:  # noqa: BLE001 — no compiler / bad toolchain
            _lib = None
        return _lib


def available():
    return _load() is not None


def checksum(buf):
    lib = _load()
    if lib is None:
        raise RuntimeError("native wire runtime unavailable")
    b = bytes(buf)
    return int(lib.coinn_checksum(b, len(b)))


def pack_file(path, header, buffers):
    """Write ``header`` then each buffer in ``buffers`` to ``path`` via the
    native gather-write.  Returns False if the native path is unavailable
    (caller should fall back)."""
    lib = _load()
    if lib is None:
        return False
    n = len(buffers)
    # keep contiguous byte views alive for the duration of the call
    views = [b if isinstance(b, bytes) else bytes(b) for b in buffers]
    bufs = (ctypes.c_char_p * n)(*views)
    sizes = (ctypes.c_uint64 * n)(*[len(v) for v in views])
    rc = lib.coinn_pack_file(
        os.fsencode(path), bytes(header), len(header),
        ctypes.cast(bufs, ctypes.POINTER(ctypes.c_char_p)), sizes, n,
    )
    return rc == 0


def load_file(path):
    """Read the whole file via the native bulk reader; None on failure."""
    lib = _load()
    if lib is None:
        return None
    out = ctypes.POINTER(ctypes.c_uint8)()
    size = lib.coinn_load_file(os.fsencode(path), ctypes.byref(out))
    if size == 0:
        if os.path.exists(path) and os.path.getsize(path) == 0:
            return b""
        return None
    try:
        return ctypes.string_at(out, size)
    finally:
        lib.coinn_free(out)


def load_many(paths):
    """Load several files concurrently (native threads, no GIL, no process
    pool — ≙ ref ``distrib/reducer.py:18-23``).  Returns list of bytes (None
    for failed entries), or None when native is unavailable."""
    lib = _load()
    if lib is None:
        return None
    n = len(paths)
    if n == 0:
        return []
    arr = (ctypes.c_char_p * n)(*[os.fsencode(p) for p in paths])
    outs = (ctypes.POINTER(ctypes.c_uint8) * n)()
    sizes = (ctypes.c_uint64 * n)()
    lib.coinn_load_many(
        ctypes.cast(arr, ctypes.POINTER(ctypes.c_char_p)), n, outs, sizes
    )
    result = []
    for i in range(n):
        if sizes[i] == 0:
            ok_empty = os.path.exists(paths[i]) and os.path.getsize(paths[i]) == 0
            result.append(b"" if ok_empty else None)
            continue
        try:
            result.append(ctypes.string_at(outs[i], sizes[i]))
        finally:
            lib.coinn_free(outs[i])
    return result
