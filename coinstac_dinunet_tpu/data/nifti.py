"""Dependency-light NIfTI-1 volume I/O for the neuroimaging data pipeline.

The reference's deployments feed VBM gray-matter maps and similar volumes
stored as ``.nii``/``.nii.gz`` (its dev guide has users write the nibabel
calls inside ``COINNDataset.__getitem__`` — ref ``data/data.py:59-64`` user
contract + README).  This module gives the framework a first-class loader:

- :func:`load_nifti` — reads a NIfTI-1 file into a numpy array, applying
  the header's ``scl_slope``/``scl_inter`` scaling.  Uses nibabel when it
  is importable; otherwise falls back to the built-in pure-numpy reader
  (this image has no nibabel — the format's fixed 348-byte header makes a
  minimal reader small and exact for the common single-file case).
- :func:`save_nifti` — writes a minimal single-file NIfTI-1 (``n+1``
  magic), enough for tests, fixtures and synthetic-data examples to
  produce files that nibabel (and this reader) load bit-exactly.

Scope: single-file NIfTI-1 (``n+1`` magic, little/big endian, gzip or
plain), the numeric dtypes that appear in practice, no extensions.  A
``.hdr``/``.img`` pair or NIfTI-2 file raises a clear error naming
nibabel as the escape hatch.
"""
import gzip
import os
import struct

import numpy as np

__all__ = ["load_nifti", "save_nifti", "HAVE_NIBABEL"]

try:  # soft import: the built-in reader is the fallback, not the default
    import nibabel as _nib

    HAVE_NIBABEL = True
except Exception:  # pragma: no cover - nibabel absent in this image
    _nib = None
    HAVE_NIBABEL = False

# NIfTI-1 datatype code → numpy dtype (the codes seen in real datasets)
_DTYPES = {
    2: np.uint8, 4: np.int16, 8: np.int32, 16: np.float32, 64: np.float64,
    256: np.int8, 512: np.uint16, 768: np.uint32, 1024: np.int64,
    1280: np.uint64,
}
_HDR_SIZE = 348


def _read_bytes(path):
    with open(path, "rb") as f:
        head = f.read(2)
        f.seek(0)
        if head == b"\x1f\x8b":
            return gzip.decompress(f.read())
        return f.read()


def load_nifti(path, dtype=None):
    """Read a NIfTI-1 volume → numpy array (x, y, z[, t]) with header
    scaling applied.  ``dtype`` casts the result (default: float32 for
    scaled/float data, the stored dtype otherwise)."""
    if _nib is not None:
        img = _nib.load(path)
        arr = np.asanyarray(img.dataobj)
        # same default rule as the built-in reader below, so the public
        # API's dtype never depends on whether nibabel is installed
        if dtype is None:
            dtype = np.float32 if arr.dtype.kind == "f" else arr.dtype
        return np.ascontiguousarray(arr, dtype=dtype)
    raw = _read_bytes(path)
    if len(raw) < _HDR_SIZE:
        raise ValueError(f"{path!r}: too short for a NIfTI-1 header")
    # endianness from sizeof_hdr (348 in the file's byte order)
    for end in ("<", ">"):
        if struct.unpack(end + "i", raw[:4])[0] == _HDR_SIZE:
            break
    else:
        raise ValueError(
            f"{path!r}: not a NIfTI-1 file (sizeof_hdr != 348); for NIfTI-2 "
            "or ANALYZE pairs install nibabel"
        )
    magic = raw[344:348]
    if not magic.startswith(b"n+1"):
        raise ValueError(
            f"{path!r}: magic {magic!r} is not single-file NIfTI-1 ('n+1'); "
            "for .hdr/.img pairs install nibabel"
        )
    dim = struct.unpack(end + "8h", raw[40:56])
    ndim = int(dim[0])
    if not 1 <= ndim <= 7:
        raise ValueError(f"{path!r}: bad ndim {ndim}")
    shape = tuple(int(d) for d in dim[1 : 1 + ndim])
    code = struct.unpack(end + "h", raw[70:72])[0]
    if code not in _DTYPES:
        raise ValueError(
            f"{path!r}: unsupported NIfTI datatype code {code}; "
            "install nibabel for exotic dtypes"
        )
    vox_offset = int(struct.unpack(end + "f", raw[108:112])[0])
    slope, inter = struct.unpack(end + "2f", raw[112:120])
    base = np.dtype(_DTYPES[code]).newbyteorder(end)
    n = int(np.prod(shape))
    arr = np.frombuffer(raw, dtype=base, count=n, offset=vox_offset)
    # NIfTI is column-major (Fortran order) on disk
    arr = arr.reshape(shape, order="F")
    # NIfTI-1 spec: scl_slope == 0 means NO scaling at all (scl_inter is
    # ignored too) — matching nibabel, so the same file loads identically
    # whether or not nibabel is installed (the API-independence contract)
    if slope != 0.0 and (slope != 1.0 or inter != 0.0):
        arr = arr * np.float32(slope) + np.float32(inter)
    if dtype is None:
        dtype = np.float32 if arr.dtype.kind == "f" else arr.dtype
    return np.ascontiguousarray(arr, dtype=dtype)


def save_nifti(path, array, pixdim=1.0):
    """Write ``array`` as a minimal single-file NIfTI-1 (no scaling, no
    extensions).  Gzips when ``path`` ends in ``.gz``.  Fixture/synthetic
    writer — real acquisitions carry affines this minimal header omits."""
    arr = np.asarray(array)
    code = next((c for c, d in _DTYPES.items() if np.dtype(d) == arr.dtype), None)
    if code is None:
        arr = arr.astype(np.float32)
        code = 16
    hdr = bytearray(_HDR_SIZE)
    struct.pack_into("<i", hdr, 0, _HDR_SIZE)
    dim = (arr.ndim, *arr.shape) + (1,) * (7 - arr.ndim)
    struct.pack_into("<8h", hdr, 40, *dim)
    struct.pack_into("<h", hdr, 70, code)
    struct.pack_into("<h", hdr, 72, arr.dtype.itemsize * 8)  # bitpix
    struct.pack_into("<8f", hdr, 76, 1.0, *([float(pixdim)] * arr.ndim),
                     *([1.0] * (7 - arr.ndim)))
    struct.pack_into("<f", hdr, 108, 352.0)  # vox_offset
    struct.pack_into("<2f", hdr, 112, 1.0, 0.0)  # scl_slope/inter
    hdr[344:348] = b"n+1\x00"
    payload = bytes(hdr) + b"\x00" * 4 + arr.tobytes(order="F")
    data = gzip.compress(payload) if str(path).endswith(".gz") else payload
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)
    return path
