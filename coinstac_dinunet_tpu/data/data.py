"""Data layer: user-subclassable dataset, static-shape batch loader, handle.

Capability parity with the reference ``data/data.py:23-242`` (COINNDataset,
safe_collate, COINNDataHandle with cursor-based ``next_iter``,
COINNPaddedDataSampler), re-designed for XLA:

- No torch DataLoader.  Batches are numpy dict-of-arrays with **static
  shapes**: the tail batch is padded to full ``batch_size`` and carries a
  ``_mask`` vector (1=real, 0=padding) — under jit, padding+masking replaces
  the reference's padded sampler, and every site can be padded to the same
  number of batches for lockstep federated epochs (ref ``data/data.py:203-242``).
- The loader is deterministic given (seed, epoch) so federated sites shuffle
  reproducibly, and its cursor is a plain int that survives across engine
  invocations in the node cache (ref ``next_iter`` ``data/data.py:175-191``).
"""
import math
import os

import numpy as np

from ..config.keys import Mode
from . import datautils


def safe_collate(samples):
    """Stack a list of sample dicts into a batch dict, dropping failed (None)
    samples (ref ``data/data.py:23-27``)."""
    samples = [s for s in samples if s is not None]
    if not samples:
        return None
    keys = samples[0].keys()
    return {k: np.stack([np.asarray(s[k]) for s in samples]) for k in keys}


class COINNDataset:
    """User-subclassable dataset.

    Users implement ``load_index(dataset_name, file)`` — inspect one input
    file and append one or more index entries via ``self.indices.append(...)``
    — and ``__getitem__(ix) -> dict`` returning numpy arrays (e.g.
    ``{'inputs': x, 'labels': y}``).
    """

    def __init__(self, mode=Mode.TRAIN, limit=None, **kw):
        self.mode = mode
        self.limit = limit or float("inf")
        self.indices = []
        self.state = {}
        self.cache = {}
        self.data_conf = {}

    # ---- user hooks ------------------------------------------------------
    def load_index(self, dataset_name, file):
        self.indices.append([dataset_name, file])

    def __getitem__(self, ix):
        raise NotImplementedError

    # ---- framework API ---------------------------------------------------
    def __len__(self):
        return len(self.indices)

    def path(self, dataset_name=None, cache_key="data_dir"):
        """Resolve a data path from the engine ``state`` + cached conf."""
        base = self.state.get(dataset_name, self.state).get("baseDirectory", ".") \
            if isinstance(self.state.get(dataset_name), dict) else self.state.get("baseDirectory", ".")
        sub = self.data_conf.get(cache_key, self.cache.get(cache_key, ""))
        return os.path.join(base, sub) if sub else base

    def add(self, files, cache=None, state=None, data_conf=None, dataset_name="site"):
        self.cache = cache or self.cache
        self.state = state or self.state
        self.data_conf = data_conf or self.data_conf
        for f in files:
            if len(self.indices) >= self.limit:
                break
            self.load_index(dataset_name, f)


class COINNDataLoader:
    """Deterministic static-shape batch iterator.

    Pads the tail batch (and optionally the whole epoch up to
    ``target_batches``, wrapping indices like the reference's padded sampler)
    and marks padded entries with ``_mask=0`` so metrics/losses ignore them.
    """

    def __init__(self, dataset, batch_size=16, shuffle=False, seed=0, epoch=0,
                 drop_last=False, target_batches=None):
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = int(seed)
        self.epoch = int(epoch)
        self.drop_last = drop_last
        n = len(dataset)
        if drop_last:
            self.num_batches = n // self.batch_size
        else:
            self.num_batches = math.ceil(n / self.batch_size)
        if target_batches is not None:
            self.num_batches = max(self.num_batches, int(target_batches))
        self._order = self._make_order()

    def _make_order(self):
        n = len(self.dataset)
        idx = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(idx)
        total = self.num_batches * self.batch_size
        if total <= n:
            order = idx[:total]
            mask = np.ones(total, dtype=np.float32)
        else:
            # wrap-pad: repeat indices to fill equal-length epochs; padding
            # beyond the real n carries mask 0 (metrics-exact lockstep)
            reps = math.ceil(total / max(n, 1)) if n else 0
            order = np.tile(idx, reps)[:total] if n else np.zeros(total, dtype=int)
            mask = np.zeros(total, dtype=np.float32)
            mask[:n] = 1.0
        return order, mask

    def __len__(self):
        return self.num_batches

    @staticmethod
    def _collate_static(samples, batch_mask):
        """Collate keeping the batch dimension STATIC: positions whose sample
        failed to load (None) are filled with a copy of a real sample and
        masked out — shapes never change, so jit never retraces."""
        keep = [s is not None for s in samples]
        if not any(keep):
            return None
        template = samples[keep.index(True)]
        filled, out_mask = [], np.array(batch_mask, dtype=np.float32)
        for i, s in enumerate(samples):
            if s is None:
                filled.append(template)
                out_mask[i] = 0.0
            else:
                filled.append(s)
        batch = safe_collate(filled)
        batch["_mask"] = out_mask
        return batch

    def __iter__(self):
        order, mask = self._order
        for b in range(self.num_batches):
            sl = slice(b * self.batch_size, (b + 1) * self.batch_size)
            samples = [self.dataset[int(i)] for i in order[sl]]
            batch = self._collate_static(samples, mask[sl])
            if batch is not None:
                yield batch

    def batch_at(self, cursor):
        """Random access for cursor-based streaming (``next_iter``)."""
        order, mask = self._order
        sl = slice(cursor * self.batch_size, (cursor + 1) * self.batch_size)
        samples = [self.dataset[int(i)] for i in order[sl]]
        return self._collate_static(samples, mask[sl])


def device_prefetch(iterator, size=2, sharding=None):
    """Overlap host-side batch assembly + host→device transfer with device
    compute: a background thread stays ``size`` batches ahead, issuing
    ``jax.device_put`` so the copy is in flight while the previous step
    runs.  HBM-bandwidth hygiene for real (non-synthetic) input pipelines —
    the training loop's dispatch never blocks on the loader.

    ``sharding``: optional ``jax.sharding.Sharding`` applied to every leaf
    (e.g. batch-axis sharding over a local data-parallel mesh) so batches
    land pre-sharded instead of committed to one device and re-sharded at
    dispatch.  An abandoned generator (consumer error/early break) stops
    the producer promptly — no thread or device-buffer leak.
    """
    import queue
    import threading

    import jax

    if int(size) <= 0:  # prefetch disabled: plain pass-through
        yield from iterator
        return
    q = queue.Queue(maxsize=int(size))
    stop = threading.Event()
    _END = object()

    def _put(item):
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _producer():
        try:
            for batch in iterator:
                placed = (jax.device_put(batch, sharding) if sharding is not None
                          else jax.device_put(batch))
                if not _put(placed):
                    return
            _put(_END)
        except BaseException as exc:  # noqa: BLE001 — re-raised by consumer
            _put(exc)

    t = threading.Thread(target=_producer, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()


class COINNDataHandle:
    """Owns per-mode datasets built from the current fold's split JSON and the
    loader configuration; provides cursor-based batch streaming that survives
    across engine invocations (ref ``data/data.py:84-200``)."""

    def __init__(self, cache=None, input=None, state=None, dataloader_args=None,
                 dataset_cls=COINNDataset):
        self.cache = cache if cache is not None else {}
        self.input = input if input is not None else {}
        self.state = state if state is not None else {}
        self.dataloader_args = dataloader_args or {}
        self.dataset_cls = dataset_cls
        self.datasets = {}

    # ---- split / dataset construction -----------------------------------
    def list_files(self):
        data_dir = os.path.join(
            self.state.get("baseDirectory", "."),
            self.cache.get("data_dir", self.cache.get("task_id", "")),
        )
        if not os.path.isdir(data_dir):
            data_dir = self.state.get("baseDirectory", ".")
        return sorted(os.listdir(data_dir))

    def prepare_data(self):
        """k-fold init (ref ``init_k_folds`` precedence)."""
        files = self.list_files()
        return datautils.init_k_folds(files, self.cache, self.state,
                                      self.cache.get("data_conf", {}))

    def get_split(self):
        import json

        split_file = self.cache["splits"][str(self.cache.get("split_ix", 0))]
        with open(os.path.join(self.cache["split_dir"], split_file)) as f:
            return json.load(f)

    def get_dataset(self, handle_key, files, mode=None):
        ds = self.dataset_cls(mode=mode or handle_key, limit=self.cache.get("load_limit"))
        ds.add(files, cache=self.cache, state=self.state,
               data_conf=self.cache.get("data_conf", {}))
        self.datasets[handle_key] = ds
        return ds

    def get_train_dataset(self):
        return self.get_dataset("train", self.get_split().get("train", []), Mode.TRAIN)

    def get_validation_dataset(self):
        return self.get_dataset("validation", self.get_split().get("validation", []), Mode.VALIDATION)

    def get_test_dataset(self, load_sparse=False):
        files = self.get_split().get("test", [])
        if load_sparse and files:
            # one dataset per file — lets save_predictions work per-subject
            out = []
            for i, f in enumerate(files):
                ds = self.dataset_cls(mode=Mode.TEST, limit=self.cache.get("load_limit"))
                ds.add([f], cache=self.cache, state=self.state,
                       data_conf=self.cache.get("data_conf", {}))
                out.append(ds)
            self.datasets["test"] = out
            return out
        return self.get_dataset("test", files, Mode.TEST)

    # ---- loaders ---------------------------------------------------------
    def get_loader(self, handle_key="train", dataset=None, **kw):
        """Merge precedence: call kwargs > per-key cached args > global args."""
        args = dict(self.dataloader_args.get(handle_key, {}))
        for k in ("batch_size", "seed"):
            if k in self.cache and k not in args:
                args[k] = self.cache[k]
        args.update(kw)
        args.setdefault("batch_size", 16)
        ds = dataset or self.datasets.get(handle_key)
        return COINNDataLoader(ds, **args)

    # ---- cursor-based streaming (engine transport) -----------------------
    def next_iter(self, out=None):
        """Return the next training batch; on epoch exhaustion reset the
        cursor and signal VALIDATION_WAITING (the epoch barrier)."""
        out = out if out is not None else {}
        cursor = int(self.cache.get("cursor", 0))
        if "train" not in self.datasets:
            self.get_train_dataset()
        loader = self.get_loader(
            "train",
            shuffle=True,
            seed=int(self.cache.get("seed", 0)),
            epoch=int(self.cache.get("epoch", 0)),
            target_batches=self.cache.get("target_batches"),
        )
        # skip over batches where every sample failed to load (batch_at → None)
        batch = None
        while cursor < len(loader) and batch is None:
            batch = loader.batch_at(cursor)
            cursor += 1
        if batch is None:
            self.cache["cursor"] = 0
            # epoch rollover: next epoch reshuffles with a fresh (seed, epoch)
            self.cache["epoch"] = int(self.cache.get("epoch", 0)) + 1
            out["mode"] = Mode.VALIDATION_WAITING.value
            return None, out
        self.cache["cursor"] = cursor
        out["mode"] = self.cache.get("mode", Mode.TRAIN.value)
        return batch, out


class EmptyDataHandle(COINNDataHandle):
    """The aggregator holds no data (ref ``remote.py:22-26``)."""

    def list_files(self):
        return []

    def prepare_data(self):
        return {}
