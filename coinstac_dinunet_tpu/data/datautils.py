"""Dataset split generation: ratio splits, rotating k-fold, precedence rules.

Capability parity with the reference ``data/datautils.py:11-98``
(create_ratio_split, create_k_fold_splits with rotating val/test folds,
split_place_holder, init_k_folds precedence: pre-supplied splits dir >
split_files > num_folds > split_ratio > placeholder).
"""
import json
import os
import shutil

import numpy as np


def create_ratio_split(files, save_to_dir=None, ratio=(0.6, 0.2, 0.2), first_key="train", name="SPLIT", seed=None):
    """Single split by ratio.  Keys ordered train/validation/test (or starting
    at ``first_key``); a 2-tuple ratio yields train/validation only."""
    keys = ["train", "validation", "test"]
    keys = keys[keys.index(first_key):]
    files = list(files)
    rng = np.random.default_rng(len(files) if seed is None else seed)
    rng.shuffle(files)
    n = len(files)
    sizes = [int(round(r * n)) for r in ratio]
    sizes[0] = n - sum(sizes[1:])  # absorb rounding into train
    split, off = {}, 0
    for key, sz in zip(keys, sizes):
        split[key] = files[off : off + sz]
        off += sz
    if save_to_dir:
        os.makedirs(save_to_dir, exist_ok=True)
        with open(os.path.join(save_to_dir, f"{name}.json"), "w") as f:
            json.dump(split, f, indent=2)
    return split


def create_k_fold_splits(files, k, save_to_dir=None, shuffle_files=True, name="SPLIT", seed=None):
    """K rotating splits: split i uses fold i as test, fold i+1 (mod k) as
    validation, the rest as train — every sample is tested exactly once."""
    files = list(files)
    if shuffle_files:
        rng = np.random.default_rng(len(files) if seed is None else seed)
        rng.shuffle(files)
    folds = [list(part) for part in np.array_split(np.asarray(files, dtype=object), k)]
    splits = []
    for i in range(k):
        test = folds[i]
        val = folds[(i + 1) % k]
        train = [f for j, fold in enumerate(folds) if j not in (i, (i + 1) % k) for f in fold]
        split = {"train": train, "validation": val, "test": test}
        splits.append(split)
        if save_to_dir:
            os.makedirs(save_to_dir, exist_ok=True)
            with open(os.path.join(save_to_dir, f"{name}_{i}.json"), "w") as f:
                json.dump(split, f, indent=2)
    return splits


def split_place_holder(files, save_to_dir=None, name="SPLIT"):
    """Everything in train — used when the task needs no held-out data."""
    split = {"train": list(files), "validation": [], "test": []}
    if save_to_dir:
        os.makedirs(save_to_dir, exist_ok=True)
        with open(os.path.join(save_to_dir, f"{name}.json"), "w") as f:
            json.dump(split, f, indent=2)
    return split


def init_k_folds(files, cache, state, data_conf=None):
    """Materialize split JSONs under ``outputDirectory/<task_id>/splits`` and
    register them in ``cache['splits']`` (index → filename).

    Precedence (highest first):
      1. ``data_conf['split_dir']`` — pre-supplied split JSONs, copied in.
      2. ``cache['split_files']`` — explicit list of split JSONs in data dir.
      3. ``cache['num_folds']`` — generate rotating k-fold splits.
      4. ``cache['split_ratio']`` — one ratio split.
      5. placeholder — everything in train.
    """
    data_conf = data_conf or {}
    out_dir = os.path.join(
        state.get("outputDirectory", "."), cache.get("task_id", "task"), "splits"
    )
    # clear stale split JSONs from a previous run with a different split config
    if os.path.isdir(out_dir):
        shutil.rmtree(out_dir)
    os.makedirs(out_dir, exist_ok=True)

    pre_dir = data_conf.get("split_dir")
    if pre_dir:
        pre_dir = os.path.join(state.get("baseDirectory", "."), pre_dir)
    if pre_dir and os.path.isdir(pre_dir) and os.listdir(pre_dir):
        for f in sorted(os.listdir(pre_dir)):
            shutil.copy(os.path.join(pre_dir, f), out_dir)
    elif cache.get("split_files"):
        for f in cache["split_files"]:
            shutil.copy(os.path.join(state.get("baseDirectory", "."), f), out_dir)
    elif cache.get("num_folds"):
        create_k_fold_splits(files, int(cache["num_folds"]), save_to_dir=out_dir,
                             seed=cache.get("seed"))
    elif cache.get("split_ratio"):
        create_ratio_split(files, save_to_dir=out_dir, ratio=tuple(cache["split_ratio"]),
                           seed=cache.get("seed"))
    else:
        split_place_holder(files, save_to_dir=out_dir)

    split_files = sorted(os.listdir(out_dir))
    cache["split_dir"] = out_dir
    cache["splits"] = {str(i): f for i, f in enumerate(split_files)}
    return cache["splits"]
