from .data import (
    COINNDataHandle,
    COINNDataLoader,
    COINNDataset,
    EmptyDataHandle,
    device_prefetch,
    safe_collate,
)
from .datautils import (
    create_k_fold_splits,
    create_ratio_split,
    init_k_folds,
    split_place_holder,
)

__all__ = [
    "COINNDataset",
    "COINNDataHandle",
    "COINNDataLoader",
    "EmptyDataHandle",
    "safe_collate",
    "device_prefetch",
    "create_k_fold_splits",
    "create_ratio_split",
    "split_place_holder",
    "init_k_folds",
]
