"""Multi-network scheme (benchmark config 5): two models trained jointly.

Exercises the dict-of-models API end-to-end: ``nn`` holds two networks, the
iteration combines their outputs, gradients for BOTH flow through every agg
engine, and checkpoints capture both (the reference silently drops all but
the last model — ``nn/basetrainer.py:103-114``, SURVEY §2 defects).
"""
import jax.numpy as jnp

from ..metrics import classification_outputs
from ..trainer import COINNTrainer
from ..utils import parse_shape
from .cnn3d import VBM3DNet


class MultiNetTrainer(COINNTrainer):
    """Two VBM CNNs (e.g. two modalities / an ensemble pair) whose logits
    fuse by averaging; one loss trains both."""

    def _init_nn_model(self):
        num_classes = int(self.cache.get("num_classes", 2))
        dtype = jnp.dtype(self.cache.setdefault("compute_dtype", "bfloat16"))
        width = int(self.cache.get("model_width", 16))
        self.nn["net_a"] = VBM3DNet(num_classes=num_classes, width=width, dtype=dtype)
        self.nn["net_b"] = VBM3DNet(num_classes=num_classes, width=width, dtype=dtype)

    def example_inputs(self):
        shape = parse_shape(self.cache.get("input_shape"), (32, 32, 32))
        x = jnp.zeros((1, *shape), jnp.float32)
        return {"net_a": (x,), "net_b": (x,)}

    def iteration(self, params, batch, rng=None):
        x = batch["inputs"]
        logits_a = self.nn["net_a"].apply(params["net_a"], x)
        logits_b = self.nn["net_b"].apply(params["net_b"], x)
        logits = 0.5 * (logits_a + logits_b)
        return classification_outputs(logits, batch["labels"], mask=batch.get("_mask"))
