"""VBM 3-D CNN classifier — the flagship benchmark model (config 3).

Voxel-based-morphometry classification: a volumetric CNN over gray-matter
maps (canonical VBM grid 121×145×121).  TPU-first choices:

- **NDHWC layout** (channels last) — XLA's native conv layout on TPU; torch's
  NCDHW would force transposes around every conv.
- **bfloat16 compute / float32 params** via ``dtype`` — convs hit the MXU at
  full rate; the loss/logits stay float32.
- **GroupNorm, not BatchNorm** — pure ``apply`` (no mutable running stats to
  keep in lockstep across federated sites) and batch-size independent.
- Strided convs instead of pooling layers where it matters (fewer HBM
  round-trips), global-average-pool head.
"""
import numpy as np

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

from ..data import COINNDataset
from ..metrics import classification_outputs
from ..trainer import COINNTrainer
from ..utils import stable_file_id


class _ConvBlock(nn.Module):
    features: int
    stride: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(
            self.features, (3, 3, 3), strides=(self.stride,) * 3,
            padding="SAME", use_bias=False, dtype=self.dtype,
        )(x)
        x = nn.GroupNorm(num_groups=min(8, self.features), dtype=self.dtype)(x)
        return nn.relu(x)


def _s2d_map():
    """(27, 64) one-hot map from the 3³ kernel taps to the block-2
    space-to-depth kernel positions.

    SAME padding for k=3, s=2 pads (0, 1), so output o reads input taps
    2o+t, t ∈ {0,1,2}; under block-2 space-to-depth that tap lives in block
    o + t//2 at in-block offset t%2.  Taps map to ((t//2 per dim) kernel
    position, (t%2 per dim) input channel); the (1,1)-per-dim positions
    stay structurally zero.
    """
    T = np.zeros((27, 64), np.float32)
    for td in range(3):
        for th in range(3):
            for tw in range(3):
                t = (td * 3 + th) * 3 + tw
                pos = ((td // 2) * 2 + th // 2) * 2 + tw // 2
                cin = (td % 2) * 4 + (th % 2) * 2 + (tw % 2)
                T[t, pos * 8 + cin] = 1.0
    return T


class _StemConv(nn.Module):
    """Stride-2 3³ conv on a 1-channel volume, executed as its block-2
    space-to-depth reparametrization (the MLPerf ResNet conv0 trick).

    A cin=1 conv underfills the TPU MXU's 128-wide contraction (XLA pads
    the size-1 channel dim onto the lanes); reshaping 2×2×2 input blocks
    into 8 channels and convolving with the equivalently remapped 2³×8
    kernel computes the SAME function (max |Δ| ≈ 3e-7 vs the plain conv)
    with a 64-deep contraction — measured −1.1 ms on the flagship step
    (batch 128 · 64³, v5e; see docs/PERF.md).
    The parameter keeps the canonical (3,3,3,1,F) shape; odd spatial dims
    fall back to the plain conv.
    """

    features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        import os

        f = self.features
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), (3, 3, 3, 1, f),
            jnp.float32,
        )
        k = jnp.asarray(kernel, self.dtype)
        b, d, h, w, _ = x.shape
        # COINN_NO_S2D: operational kill-switch to the plain-conv path
        # (identical math) should a backend mis-handle the remapped kernel
        no_s2d = os.environ.get("COINN_NO_S2D", "").lower() not in ("", "0", "false")
        if no_s2d or d % 2 or h % 2 or w % 2:
            return lax.conv_general_dilated(
                x, k, (2, 2, 2), "SAME",
                dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
            )
        k2 = (
            jnp.asarray(_s2d_map(), self.dtype).T @ k.reshape(27, f)
        ).reshape(2, 2, 2, 8, f)
        xs = x.reshape(b, d // 2, 2, h // 2, 2, w // 2, 2, 1)
        xs = xs.transpose(0, 1, 3, 5, 2, 4, 6, 7)
        xs = xs.reshape(b, d // 2, h // 2, w // 2, 8)
        return lax.conv_general_dilated(
            xs, k2, (1, 1, 1), ((0, 1), (0, 1), (0, 1)),
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
        )


class VBM3DNet(nn.Module):
    """Volumetric CNN: stem + 4 strided stages + GAP head."""

    num_classes: int = 2
    width: int = 16
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=False, rng=None):
        # x: (B, D, H, W) or (B, D, H, W, 1)
        if x.ndim == 4:
            x = x[..., None]
        x = jnp.asarray(x, self.dtype)
        w = self.width
        # stem: space-to-depth stride-2 conv (see _StemConv) + GN + relu
        x = _StemConv(w, dtype=self.dtype)(x)  # /2
        x = nn.GroupNorm(num_groups=min(8, w), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = _ConvBlock(w, dtype=self.dtype)(x)
        x = _ConvBlock(2 * w, stride=2, dtype=self.dtype)(x)  # /4
        x = _ConvBlock(2 * w, dtype=self.dtype)(x)
        x = _ConvBlock(4 * w, stride=2, dtype=self.dtype)(x)  # /8
        x = _ConvBlock(4 * w, dtype=self.dtype)(x)
        x = _ConvBlock(8 * w, stride=2, dtype=self.dtype)(x)  # /16
        x = jnp.mean(x, axis=(1, 2, 3))  # global average pool
        x = jnp.asarray(x, jnp.float32)
        if train and rng is not None:
            x = nn.Dropout(0.2, deterministic=False)(x, rng=rng)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


class SyntheticVBMDataset(COINNDataset):
    """Deterministic synthetic VBM volumes keyed by file id (benches/tests).

    Real data: subclass and override ``__getitem__`` to load NIfTI/npy maps.
    """

    def __getitem__(self, ix):
        _, file = self.indices[ix]
        shape = tuple(self.cache.get("input_shape", (32, 32, 32)))
        fid = stable_file_id(file)
        rng = np.random.default_rng(fid)
        y = fid % int(self.cache.get("num_classes", 2))
        x = rng.normal(loc=0.05 * y, scale=1.0, size=shape).astype(np.float32)
        return {"inputs": x, "labels": np.int32(y)}


class VBMTrainer(COINNTrainer):
    def _init_nn_model(self):
        self.nn["vbm_net"] = VBM3DNet(
            num_classes=int(self.cache.get("num_classes", 2)),
            width=int(self.cache.get("model_width", 16)),
            dtype=jnp.dtype(self.cache.setdefault("compute_dtype", "bfloat16")),
        )

    def example_inputs(self):
        shape = tuple(self.cache.get("input_shape", (32, 32, 32)))
        return {"vbm_net": (jnp.zeros((1, *shape), jnp.float32),)}

    def iteration(self, params, batch, rng=None):
        logits = self.nn["vbm_net"].apply(
            params["vbm_net"], batch["inputs"], train=rng is not None, rng=rng
        )
        return classification_outputs(logits, batch["labels"], mask=batch.get("_mask"))
