"""VBM 3-D CNN classifier — the flagship benchmark model (config 3).

Voxel-based-morphometry classification: a volumetric CNN over gray-matter
maps (canonical VBM grid 121×145×121).  TPU-first choices:

- **NDHWC layout** (channels last) — XLA's native conv layout on TPU; torch's
  NCDHW would force transposes around every conv.
- **bfloat16 compute / float32 params** via ``dtype`` — convs hit the MXU at
  full rate; the loss/logits stay float32.
- **GroupNorm, not BatchNorm** — pure ``apply`` (no mutable running stats to
  keep in lockstep across federated sites) and batch-size independent.
- Strided convs instead of pooling layers where it matters (fewer HBM
  round-trips), global-average-pool head.
"""
import os

import numpy as np

import flax.linen as nn
import jax.numpy as jnp

from ..data import COINNDataset
from ..metrics import classification_outputs
from ..ops.groupnorm import norm_relu
from ..trainer import COINNTrainer
from ..utils import logger, parse_shape, stable_file_id


class _ConvBlock(nn.Module):
    features: int
    stride: int = 1
    dtype: jnp.dtype = jnp.bfloat16
    fused_gn: bool = False

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(
            self.features, (3, 3, 3), strides=(self.stride,) * 3,
            padding="SAME", use_bias=False, dtype=self.dtype,
        )(x)
        # fused GN+ReLU with the closed-form backward (docs/PERF.md GN
        # lever); the shared dispatch pins the nn.GroupNorm param path
        return norm_relu(x, self.features, self.dtype, self.fused_gn, True,
                         "GroupNorm_0")


class _StemConv(nn.Module):
    """Stride-2 3³ conv on a 1-channel volume, executed as its block-2
    space-to-depth reparametrization (the MLPerf ResNet conv0 trick).

    A cin=1 conv underfills the TPU MXU's 128-wide contraction (XLA pads
    the size-1 channel dim onto the lanes); reshaping 2×2×2 input blocks
    into 8 channels and convolving with the equivalently remapped 2³×8
    kernel computes the SAME function (max |Δ| ≈ 3e-7 vs the plain conv)
    with a 64-deep contraction — measured −1.1 ms on the flagship step
    (batch 128 · 64³, v5e; see docs/PERF.md).
    The parameter keeps the canonical (3,3,3,1,F) shape; odd spatial dims
    fall back to the plain conv.
    """

    features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        from ..ops.s2d import stride2_conv

        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (3, 3, 3, 1, self.features), jnp.float32,
        )
        return stride2_conv(x, jnp.asarray(kernel, self.dtype))


class VBM3DNet(nn.Module):
    """Volumetric CNN: stem + 4 strided stages + GAP head.

    ``width`` sets the channel progression (w, 2w, 4w, 8w).  The default 16
    is the benchmark flagship; ``width=32`` fills the MXU's 128 output
    lanes from stage 2 on (higher MFU at more FLOPs/sample — report both,
    docs/PERF.md).  ``fused_gn`` routes every norm through the fused
    GroupNorm(+ReLU) with the closed-form backward — exact, but measured
    SLOWER on-device than XLA's autodiff of flax GroupNorm (it splits
    fusions with the adjacent convs; round-5 A/B in docs/PERF.md), so it
    defaults OFF (opt in with ``cache['fused_groupnorm']=True``; env kill
    switch ``COINN_NO_FUSED_GN`` still forces it off).
    """

    num_classes: int = 2
    width: int = 16
    dtype: jnp.dtype = jnp.bfloat16
    fused_gn: bool = False

    @nn.compact
    def __call__(self, x, train=False, rng=None):
        import os

        fused = self.fused_gn and not os.environ.get("COINN_NO_FUSED_GN")
        # x: (B, D, H, W) or (B, D, H, W, 1)
        if x.ndim == 4:
            x = x[..., None]
        x = jnp.asarray(x, self.dtype)
        w = self.width
        # stem: space-to-depth stride-2 conv (see _StemConv) + GN + relu
        x = _StemConv(w, dtype=self.dtype)(x)  # /2
        x = norm_relu(x, w, self.dtype, fused, True, "GroupNorm_0")
        x = _ConvBlock(w, dtype=self.dtype, fused_gn=fused)(x)
        x = _ConvBlock(2 * w, stride=2, dtype=self.dtype, fused_gn=fused)(x)  # /4
        x = _ConvBlock(2 * w, dtype=self.dtype, fused_gn=fused)(x)
        x = _ConvBlock(4 * w, stride=2, dtype=self.dtype, fused_gn=fused)(x)  # /8
        x = _ConvBlock(4 * w, dtype=self.dtype, fused_gn=fused)(x)
        x = _ConvBlock(8 * w, stride=2, dtype=self.dtype, fused_gn=fused)(x)  # /16
        x = jnp.mean(x, axis=(1, 2, 3))  # global average pool
        x = jnp.asarray(x, jnp.float32)
        if train and rng is not None:
            x = nn.Dropout(0.2, deterministic=False)(x, rng=rng)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


class SyntheticVBMDataset(COINNDataset):
    """Deterministic synthetic VBM volumes keyed by file id (benches/tests).

    Real data: subclass and override ``__getitem__`` to load NIfTI/npy maps.
    """

    def __getitem__(self, ix):
        _, file = self.indices[ix]
        shape = parse_shape(self.cache.get("input_shape"), (32, 32, 32))
        fid = stable_file_id(file)
        rng = np.random.default_rng(fid)
        y = fid % int(self.cache.get("num_classes", 2))
        x = rng.normal(loc=0.05 * y, scale=1.0, size=shape).astype(np.float32)
        return {"inputs": x, "labels": np.int32(y)}


def fit_volume(arr, shape):
    """Center-crop/zero-pad a volume to ``shape`` (static shapes are an XLA
    requirement — every subject must land on the same grid)."""
    arr = np.asarray(arr)
    if arr.ndim != len(shape):
        raise ValueError(
            f"volume is {arr.ndim}-D {arr.shape} but the target grid is "
            f"{len(shape)}-D {tuple(shape)} — a 4-D (fMRI timeseries?) "
            "input needs an explicit time-axis reduction before fitting"
        )
    out = np.zeros(shape, arr.dtype)
    src, dst = [], []
    for a, s in zip(arr.shape, shape):
        if a >= s:
            o = (a - s) // 2
            src.append(slice(o, o + s)); dst.append(slice(0, s))
        else:
            o = (s - a) // 2
            src.append(slice(0, a)); dst.append(slice(o, o + a))
    out[tuple(dst)] = arr[tuple(src)]
    return out


class NiftiVBMDataset(COINNDataset):
    """Real neuroimaging input pipeline: one ``.nii``/``.nii.gz`` gray-matter
    map per subject + a ``labels.csv`` (``filename,label`` rows) in the data
    directory — the COINSTAC deployment shape the reference's dev guide has
    users hand-write with nibabel inside ``__getitem__`` (ref
    ``data/data.py:59-64`` user contract).

    - ``load_index`` indexes only volumes that carry a label (a stray file
      in the directory is skipped with a warning rather than crashing the
      fold at train time);
    - ``__getitem__`` reads the volume (:func:`~..data.nifti.load_nifti`;
      nibabel when installed, the built-in NIfTI-1 reader otherwise) and
      center-crops/pads to ``cache['input_shape']`` — every subject lands
      on the same static grid, which XLA requires;
    - volumes are z-scored per subject unless ``cache['normalize']`` is
      falsy (VBM maps arrive in arbitrary intensity scales per site).

    Host-side loading overlaps device compute through the loader's
    ``device_prefetch`` stage like every other dataset.
    """

    def _labels(self):
        if "_nifti_labels" not in self.__dict__:
            import csv

            table = {}
            path = os.path.join(
                self.path(), str(self.cache.get("labels_file", "labels.csv"))
            )
            with open(path) as f:
                for row in csv.reader(f):
                    if len(row) >= 2 and row[1].strip().lstrip("-").isdigit():
                        table[row[0].strip()] = int(row[1])
            self._nifti_labels = table
        return self._nifti_labels

    def load_index(self, dataset_name, file):
        if not str(file).endswith((".nii", ".nii.gz")):
            return
        if str(file) not in self._labels():
            logger.warn(f"{file}: no label in labels.csv; skipped")
            return
        self.indices.append([dataset_name, file])

    def __getitem__(self, ix):
        from ..data.nifti import load_nifti

        _, file = self.indices[ix]
        shape = parse_shape(self.cache.get("input_shape"), (32, 32, 32))
        x = load_nifti(os.path.join(self.path(), str(file)), dtype=np.float32)
        x = fit_volume(np.squeeze(x), shape)
        if self.cache.get("normalize", True):
            x = (x - x.mean()) / max(float(x.std()), 1e-6)
        return {"inputs": x.astype(np.float32),
                "labels": np.int32(self._labels()[str(file)])}


class VBMTrainer(COINNTrainer):
    def _init_nn_model(self):
        self.nn["vbm_net"] = VBM3DNet(
            num_classes=int(self.cache.get("num_classes", 2)),
            width=int(self.cache.get("model_width", 16)),
            dtype=jnp.dtype(self.cache.setdefault("compute_dtype", "bfloat16")),
            fused_gn=bool(self.cache.get("fused_groupnorm", False)),
        )

    def example_inputs(self):
        shape = parse_shape(self.cache.get("input_shape"), (32, 32, 32))
        return {"vbm_net": (jnp.zeros((1, *shape), jnp.float32),)}

    def iteration(self, params, batch, rng=None):
        logits = self.nn["vbm_net"].apply(
            params["vbm_net"], batch["inputs"], train=rng is not None, rng=rng
        )
        return classification_outputs(logits, batch["labels"], mask=batch.get("_mask"))
