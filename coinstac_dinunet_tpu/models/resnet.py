"""ResNet-18 image classifier (benchmark config 4).

TPU-first flax implementation: NHWC, GroupNorm (pure apply — no federated
batch-stat drift), bfloat16 compute, 3×3 MXU-friendly convs.  Every norm
routes through the fused GroupNorm with the closed-form backward
(``ops/groupnorm.py``; same kill switches as the flagship), with param
paths pinned to the plain ``nn.GroupNorm`` layout.
"""
import os

import numpy as np

import flax.linen as nn
import jax.numpy as jnp

from ..data import COINNDataset
from ..metrics import classification_outputs
from ..trainer import COINNTrainer
from ..utils import parse_shape, stable_file_id


from ..ops.groupnorm import norm_relu as _norm  # shared fused/plain dispatch


class _ResBlock(nn.Module):
    features: int
    stride: int = 1
    dtype: jnp.dtype = jnp.bfloat16
    fused_gn: bool = False

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.features, (3, 3), strides=(self.stride,) * 2,
                    padding="SAME", use_bias=False, dtype=self.dtype)(x)
        y = _norm(y, self.features, self.dtype, self.fused_gn, True,
                  "GroupNorm_0")
        y = nn.Conv(self.features, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(y)
        y = _norm(y, self.features, self.dtype, self.fused_gn, False,
                  "GroupNorm_1")
        if residual.shape != y.shape:
            residual = nn.Conv(self.features, (1, 1), strides=(self.stride,) * 2,
                               use_bias=False, dtype=self.dtype)(x)
            residual = _norm(residual, self.features, self.dtype,
                             self.fused_gn, False, "GroupNorm_2")
        return nn.relu(y + residual)


class _Stem2D(nn.Module):
    """7×7 stride-2 stem conv on a 3-channel image, run as its block-2
    space-to-depth reparametrization when shapes allow (cin=3 underfills
    the MXU contraction; the remapped 4×4×12 kernel computes the identical
    function — :mod:`..ops.s2d`).  Parameter keeps the canonical
    ``(7, 7, cin, F)`` shape; ``COINN_NO_S2D=1`` or odd dims fall back."""

    features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        from ..ops.s2d import stride2_conv

        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (7, 7, x.shape[-1], self.features), jnp.float32,
        )
        return stride2_conv(x, jnp.asarray(kernel, self.dtype))


class ResNet18(nn.Module):
    num_classes: int = 2
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    fused_gn: bool = False

    @nn.compact
    def __call__(self, x, train=False, rng=None):
        fused = self.fused_gn and not os.environ.get("COINN_NO_FUSED_GN")
        if x.ndim == 3:
            x = x[..., None]
        x = jnp.asarray(x, self.dtype)
        w = self.width
        # name="Conv_0" keeps the flax param path of the plain nn.Conv stem
        # this replaces, so checkpoints from either version interchange
        x = _Stem2D(w, dtype=self.dtype, name="Conv_0")(x)
        x = _norm(x, w, self.dtype, fused, True, "GroupNorm_0")
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, (feat, blocks) in enumerate(
            [(w, 2), (2 * w, 2), (4 * w, 2), (8 * w, 2)]
        ):
            for b in range(blocks):
                stride = 2 if (i > 0 and b == 0) else 1
                x = _ResBlock(feat, stride=stride, dtype=self.dtype,
                              fused_gn=fused)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(
            jnp.asarray(x, jnp.float32)
        )


class SyntheticImageDataset(COINNDataset):
    """Deterministic synthetic images keyed by file id (benches/tests)."""

    def __getitem__(self, ix):
        _, file = self.indices[ix]
        shape = parse_shape(self.cache.get("input_shape"), (64, 64, 3))
        fid = stable_file_id(file)
        rng = np.random.default_rng(fid)
        y = fid % int(self.cache.get("num_classes", 2))
        x = rng.normal(loc=0.05 * y, size=shape).astype(np.float32)
        return {"inputs": x, "labels": np.int32(y)}


class ResNetTrainer(COINNTrainer):
    def _init_nn_model(self):
        self.nn["resnet"] = ResNet18(
            num_classes=int(self.cache.get("num_classes", 2)),
            width=int(self.cache.get("model_width", 64)),
            dtype=jnp.dtype(self.cache.setdefault("compute_dtype", "bfloat16")),
            fused_gn=bool(self.cache.get("fused_groupnorm", False)),
        )

    def example_inputs(self):
        shape = parse_shape(self.cache.get("input_shape"), (64, 64, 3))
        return {"resnet": (jnp.zeros((1, *shape), jnp.float32),)}

    def iteration(self, params, batch, rng=None):
        logits = self.nn["resnet"].apply(
            params["resnet"], batch["inputs"], train=rng is not None, rng=rng
        )
        return classification_outputs(logits, batch["labels"], mask=batch.get("_mask"))
