"""Sequence transformer classifier — the long-context model family.

No counterpart exists in the reference (its models are CNN/MLP classifiers;
SURVEY.md §5 records long-context as absent) — this family exists so the TPU
framework's sequence/context parallelism is exercised by a real workload:
fMRI-timeseries-style sequence classification, with attention running through
the fused :func:`~..ops.flash_attention.flash_attention` kernel and, under
the mesh transport, :func:`~..parallel.ring_attention.ring_attention` over
the ``sp`` axis (see ``parallel/sequence.py``).

Layout choices are TPU-first: head_dim and d_model multiples of 128 when
sized up, bf16 compute with f32 params, GroupNorm-free (LayerNorm is fine in
pure functional form), learned positional embedding.
"""
import numpy as np

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

from ..data import COINNDataset
from ..metrics import classification_outputs
from ..ops import flash_attention
from ..trainer import COINNTrainer
from ..utils import stable_file_id


class MultiHeadSelfAttention(nn.Module):
    """Self-attention over (B, T, D) through the fused flash kernel.

    ``sp_axis`` switches to exact global ring attention over that mesh axis
    (the module then sees only this rank's sequence block and MUST be traced
    inside a ``shard_map`` binding the axis — see ``parallel/seq_mesh.py``).
    Parameters are identical either way, so one checkpoint serves both.
    """

    num_heads: int
    causal: bool = False
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = None  # None → platform default (pallas on TPU)
    sp_axis: str = None  # sequence-parallel mesh axis (ring attention)

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        assert d % self.num_heads == 0, "num_heads must divide d_model"
        hd = d // self.num_heads
        qkv = nn.Dense(3 * d, use_bias=False, dtype=self.dtype)(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        split = lambda a: a.reshape(b, t, self.num_heads, hd).transpose(0, 2, 1, 3)
        if self.sp_axis:
            from ..parallel.ring_attention import ring_attention

            out = ring_attention(
                split(q), split(k), split(v), axis_name=self.sp_axis,
                causal=self.causal, impl=self.attn_impl,
            )
        else:
            out = flash_attention(
                split(q), split(k), split(v), causal=self.causal,
                impl=self.attn_impl,
            )
        out = out.transpose(0, 2, 1, 3).reshape(b, t, d)
        return nn.Dense(d, use_bias=False, dtype=self.dtype)(out)


class TransformerBlock(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    causal: bool = False
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = None
    sp_axis: str = None

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        h = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + MultiHeadSelfAttention(
            self.num_heads, self.causal, self.dtype, self.attn_impl,
            self.sp_axis,
        )(h)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(self.mlp_ratio * d, dtype=self.dtype)(h)
        h = nn.gelu(h)
        return x + nn.Dense(d, dtype=self.dtype)(h)


class SeqClassifier(nn.Module):
    """Encoder over continuous feature sequences → mean-pool → classes.

    With ``sp_axis`` set the module computes the SAME function on a
    sequence-sharded input (this rank's ``(B, T/sp, F)`` block, inside a
    ``shard_map``): attention rings over the axis, the positional table is
    sliced at this rank's global offset, and the mean-pool reduces over the
    axis.  Parameter shapes are independent of ``sp_axis``.
    """

    num_classes: int = 2
    d_model: int = 128
    num_heads: int = 4
    num_layers: int = 2
    max_len: int = 4096
    causal: bool = False
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = None
    sp_axis: str = None

    @nn.compact
    def __call__(self, x):
        # x: (B, T, F) continuous features (e.g. ROI timeseries); under
        # sequence parallelism T is this rank's block of the global sequence
        x = jnp.asarray(x, self.dtype)
        b, t, _ = x.shape
        x = nn.Dense(self.d_model, dtype=self.dtype)(x)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (self.max_len, self.d_model)
        )
        if self.sp_axis:
            # axis_size and t are static: fail at trace time like the
            # unsharded path's pos[:t] shape error would — dynamic_slice
            # would otherwise CLAMP the out-of-range offset and silently
            # reuse block-0 positions
            t_global = t * lax.axis_size(self.sp_axis)
            if t_global > self.max_len:
                raise ValueError(
                    f"global sequence length {t_global} exceeds max_len "
                    f"{self.max_len}"
                )
            offset = lax.axis_index(self.sp_axis) * t
            pslice = lax.dynamic_slice_in_dim(pos, offset, t, axis=0)
            x = x + pslice[None].astype(self.dtype)
        else:
            x = x + pos[:t][None].astype(self.dtype)
        for _ in range(self.num_layers):
            x = TransformerBlock(
                self.num_heads, causal=self.causal, dtype=self.dtype,
                attn_impl=self.attn_impl, sp_axis=self.sp_axis,
            )(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        if self.sp_axis:
            t_global = t * lax.axis_size(self.sp_axis)
            pooled = lax.psum(jnp.sum(x, axis=1), self.sp_axis) / t_global
        else:
            pooled = jnp.mean(x, axis=1)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(pooled)


class SyntheticSeqDataset(COINNDataset):
    """Deterministic synthetic sequence-classification samples.

    Class signal is a low-frequency sinusoid mixed into white noise — linearly
    separable only through temporal context, so attention quality actually
    moves the metric.
    """

    def __getitem__(self, ix):
        _, file = self.indices[ix]
        t = int(self.cache.get("seq_len", 128))
        f = int(self.cache.get("num_features", 16))
        n_cls = int(self.cache.get("num_classes", 2))
        fid = stable_file_id(file)
        rng = np.random.default_rng(fid)
        y = fid % n_cls
        ts = np.arange(t)[:, None] / t
        signal = np.sin(2 * np.pi * (y + 1) * ts)
        x = (rng.normal(size=(t, f)) * 0.5 + signal).astype(np.float32)
        return {"inputs": x, "labels": np.int32(y)}


class SeqTrainer(COINNTrainer):
    """Trainer wiring for the sequence family (same contract as FSVTrainer).

    Implements ``iteration_sharded``, so the federated mesh transport can
    shard each site's sequences over an ``sp`` axis (ring attention inside
    ``MeshFederation``'s compiled round — ``cache['sequence_parallel']``,
    ``parallel/seq_mesh.py``) with the full trainer stack: optax update,
    metrics, checkpoints — one checkpoint format across sp values.
    """

    def _build_model(self, sp_axis=None):
        return SeqClassifier(
            num_classes=int(self.cache.get("num_classes", 2)),
            d_model=int(self.cache.get("d_model", 128)),
            num_heads=int(self.cache.get("num_heads", 4)),
            num_layers=int(self.cache.get("num_layers", 2)),
            max_len=int(self.cache.get("max_len", 4096)),
            causal=bool(self.cache.get("causal", False)),
            dtype=jnp.dtype(self.cache.setdefault("compute_dtype", "float32")),
            attn_impl=self.cache.get("attn_impl"),
            sp_axis=sp_axis,
        )

    def _init_nn_model(self):
        self.nn["seq_net"] = self._build_model()

    def iteration_sharded(self, params, batch, rng=None, sp_axis=None):
        if sp_axis is None:
            return self.iteration(params, batch, rng)
        model = self._build_model(sp_axis=sp_axis)
        logits = model.apply(params["seq_net"], batch["inputs"])
        return classification_outputs(
            logits, batch["labels"], mask=batch.get("_mask")
        )

    def example_inputs(self):
        x = jnp.zeros(
            (1, int(self.cache.get("seq_len", 128)),
             int(self.cache.get("num_features", 16))),
            jnp.float32,
        )
        return {"seq_net": (x,)}

    def iteration(self, params, batch, rng=None):
        logits = self.nn["seq_net"].apply(params["seq_net"], batch["inputs"])
        return classification_outputs(logits, batch["labels"], mask=batch.get("_mask"))
