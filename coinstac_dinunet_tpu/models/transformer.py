"""Sequence transformer classifier — the long-context model family.

No counterpart exists in the reference (its models are CNN/MLP classifiers;
SURVEY.md §5 records long-context as absent) — this family exists so the TPU
framework's sequence/context parallelism is exercised by a real workload:
fMRI-timeseries-style sequence classification, with attention running through
the fused :func:`~..ops.flash_attention.flash_attention` kernel and, under
the mesh transport, :func:`~..parallel.ring_attention.ring_attention` over
the ``sp`` axis (see ``parallel/sequence.py``).

Layout choices are TPU-first: head_dim and d_model multiples of 128 when
sized up, bf16 compute with f32 params, GroupNorm-free (LayerNorm is fine in
pure functional form), learned positional embedding.
"""
import numpy as np

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

from ..data import COINNDataset
from ..utils.jax_compat import axis_size
from ..metrics import classification_outputs
from ..ops import flash_attention
from ..trainer import COINNTrainer
from ..utils import stable_file_id


class TPDense(nn.Module):
    """Dense layer whose MATMUL can shard over a tensor-parallel mesh axis
    while its PARAMETERS stay full-shape and replicated.

    Megatron-style column/row parallelism, adapted to the federated setting:
    every rank stores the whole kernel (so checkpoints, the cross-site
    replication invariant, and the dSGD/PowerSGD aggregation plane are all
    independent of ``tp``) but COMPUTES only its slice — 1/tp of the FLOPs
    and 1/tp of the intermediate activation memory, which is where the
    transformer's cost lives; the weights themselves are small here.

    - ``mode='col'``: output features shard; rank r computes
      ``x @ kernel[:, r-th column block]``.  ``groups=g`` slices each of
      ``g`` equal feature blocks separately (a fused qkv projection must
      shard per-head WITHIN q, k and v, not across the concatenation).
    - ``mode='row'``: input features are sharded; rank r multiplies its
      activation shard by its kernel row block, and a ``psum`` over the
      axis assembles the output.  The bias enters as ``bias/tp`` per rank
      BEFORE the psum, so the forward value is exactly ``+bias``.

    Gradient assembly across ``tp`` is a uniform ``pmean`` — exact for
    sliced and replicated leaves alike; see the cotangent derivation in
    ``parallel/tp_mesh.py``'s module docstring.

    With ``tp_axis=None`` this is exactly ``nn.Dense`` (same init, same
    math, same param shapes) — one param tree serves every tp value.
    """

    features: int
    mode: str = "col"
    groups: int = 1
    use_bias: bool = True
    dtype: jnp.dtype = jnp.float32
    tp_axis: str = None

    @nn.compact
    def __call__(self, x):
        d_local = x.shape[-1]
        n = axis_size(self.tp_axis) if self.tp_axis else 1
        # row mode sees a feature-sharded input: the stored kernel is the
        # full (d_global, features) matrix
        d_in = d_local * n if (self.tp_axis and self.mode == "row") else d_local
        # param dtype pinned f32 like nn.Dense's param_dtype default (under
        # jax_enable_x64 an unpinned initializer would draw f64 — different
        # values, breaking the one-tree-for-every-tp invariant)
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), (d_in, self.features),
            jnp.float32,
        )
        bias = (self.param("bias", nn.initializers.zeros, (self.features,),
                           jnp.float32)
                if self.use_bias else None)
        kernel = kernel.astype(self.dtype)
        x = jnp.asarray(x, self.dtype)
        if not self.tp_axis:
            y = x @ kernel
            return y + bias.astype(self.dtype) if bias is not None else y
        r = lax.axis_index(self.tp_axis)
        if self.mode == "col":
            g, f = self.groups, self.features // self.groups
            # config validation must survive ``python -O`` (a stripped
            # assert would let a mis-sized config reach dynamic_slice with
            # silently wrong slices) — so ValueError, never assert
            if f % n != 0:
                raise ValueError(
                    f"tp={n} must divide the per-group features {f}"
                )
            fl = f // n
            # (d, g*f) → (d, g, f) → this rank's (d, g, f/n) → (d, g*f/n)
            k3 = kernel.reshape(d_in, g, f)
            kl = lax.dynamic_slice_in_dim(k3, r * fl, fl, axis=2)
            y = x @ kl.reshape(d_in, g * fl)
            if bias is not None:
                b3 = bias.reshape(g, f)
                blocal = lax.dynamic_slice_in_dim(b3, r * fl, fl, axis=1)
                y = y + blocal.reshape(g * fl).astype(self.dtype)
            return y
        if self.mode != "row":
            raise ValueError(f"unknown TPDense mode {self.mode!r}")
        kl = lax.dynamic_slice_in_dim(kernel, r * d_local, d_local, axis=0)
        y = x @ kl
        if bias is not None:
            y = y + (bias / n).astype(self.dtype)
        return lax.psum(y, self.tp_axis)


class MultiHeadSelfAttention(nn.Module):
    """Self-attention over (B, T, D) through the fused flash kernel.

    ``sp_axis`` switches to exact global ring attention over that mesh axis
    (the module then sees only this rank's sequence block and MUST be traced
    inside a ``shard_map`` binding the axis — see ``parallel/seq_mesh.py``).
    ``tp_axis`` shards the HEADS over that mesh axis instead (Megatron
    attention: column-parallel qkv by head groups, local flash attention on
    this rank's heads, row-parallel output projection) — see
    ``parallel/tp_mesh.py``.  Parameters are identical in every mode, so one
    checkpoint serves all of them.

    Axis names passed into ``sp_axis``/``tp_axis`` must come from the
    :class:`~..config.keys.MeshAxis` vocabulary (``MeshAxis.SP`` /
    ``MeshAxis.TP``) — the mesh transports bind exactly those names, and the
    ``sharding-*`` lint family cross-checks every literal against them.
    """

    num_heads: int
    causal: bool = False
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = None  # None → platform default (pallas on TPU)
    sp_axis: str = None  # sequence-parallel mesh axis (MeshAxis.SP)
    tp_axis: str = None  # tensor-parallel mesh axis (MeshAxis.TP)

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        # ValueError (not assert): these config checks gate dynamic_slice
        # sizing and must survive ``python -O``
        if d % self.num_heads != 0:
            raise ValueError(
                f"num_heads={self.num_heads} must divide d_model={d}"
            )
        hd = d // self.num_heads
        heads = self.num_heads
        if self.tp_axis:
            n = axis_size(self.tp_axis)
            if heads % n != 0:
                raise ValueError(
                    f"tp={n} must divide num_heads={heads}"
                )
            heads = heads // n
        # qkv groups=3: each of q/k/v slices by this rank's head block.
        # Explicit name= keeps the historical nn.Dense param keys, so
        # checkpoints from before the TPDense swap keep loading.
        qkv = TPDense(3 * d, mode="col", groups=3, use_bias=False,
                      dtype=self.dtype, tp_axis=self.tp_axis,
                      name="Dense_0")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        split = lambda a: a.reshape(b, t, heads, hd).transpose(0, 2, 1, 3)
        if self.sp_axis:
            from ..parallel.ring_attention import ring_attention

            out = ring_attention(
                split(q), split(k), split(v), axis_name=self.sp_axis,
                causal=self.causal, impl=self.attn_impl,
            )
        else:
            out = flash_attention(
                split(q), split(k), split(v), causal=self.causal,
                impl=self.attn_impl,
            )
        out = out.transpose(0, 2, 1, 3).reshape(b, t, heads * hd)
        return TPDense(d, mode="row", use_bias=False, dtype=self.dtype,
                       tp_axis=self.tp_axis, name="Dense_1")(out)


class TransformerBlock(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    causal: bool = False
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = None
    sp_axis: str = None
    tp_axis: str = None

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        h = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + MultiHeadSelfAttention(
            self.num_heads, self.causal, self.dtype, self.attn_impl,
            self.sp_axis, self.tp_axis,
        )(h)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        # Megatron MLP: column-parallel up (gelu on the local feature
        # shard is exact — elementwise), row-parallel down with one psum.
        # name= preserves the pre-TPDense checkpoint keys.
        h = TPDense(self.mlp_ratio * d, mode="col", dtype=self.dtype,
                    tp_axis=self.tp_axis, name="Dense_0")(h)
        h = nn.gelu(h)
        return x + TPDense(d, mode="row", dtype=self.dtype,
                           tp_axis=self.tp_axis, name="Dense_1")(h)


class SeqClassifier(nn.Module):
    """Encoder over continuous feature sequences → mean-pool → classes.

    With ``sp_axis`` set the module computes the SAME function on a
    sequence-sharded input (this rank's ``(B, T/sp, F)`` block, inside a
    ``shard_map``): attention rings over the axis, the positional table is
    sliced at this rank's global offset, and the mean-pool reduces over the
    axis.  Parameter shapes are independent of ``sp_axis``.
    """

    num_classes: int = 2
    d_model: int = 128
    num_heads: int = 4
    num_layers: int = 2
    max_len: int = 4096
    causal: bool = False
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = None
    sp_axis: str = None
    tp_axis: str = None

    @nn.compact
    def __call__(self, x):
        if self.sp_axis and self.tp_axis:
            raise ValueError(
                "sp_axis and tp_axis are mutually exclusive in this model "
                "(one intra-site mesh axis); pick sequence OR tensor "
                "parallelism per run"
            )
        # x: (B, T, F) continuous features (e.g. ROI timeseries); under
        # sequence parallelism T is this rank's block of the global sequence
        x = jnp.asarray(x, self.dtype)
        b, t, _ = x.shape
        x = nn.Dense(self.d_model, dtype=self.dtype)(x)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (self.max_len, self.d_model)
        )
        if self.sp_axis:
            # axis_size and t are static: fail at trace time like the
            # unsharded path's pos[:t] shape error would — dynamic_slice
            # would otherwise CLAMP the out-of-range offset and silently
            # reuse block-0 positions
            t_global = t * axis_size(self.sp_axis)
            if t_global > self.max_len:
                raise ValueError(
                    f"global sequence length {t_global} exceeds max_len "
                    f"{self.max_len}"
                )
            offset = lax.axis_index(self.sp_axis) * t
            pslice = lax.dynamic_slice_in_dim(pos, offset, t, axis=0)
            x = x + pslice[None].astype(self.dtype)
        else:
            x = x + pos[:t][None].astype(self.dtype)
        for _ in range(self.num_layers):
            x = TransformerBlock(
                self.num_heads, causal=self.causal, dtype=self.dtype,
                attn_impl=self.attn_impl, sp_axis=self.sp_axis,
                tp_axis=self.tp_axis,
            )(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        if self.sp_axis:
            t_global = t * axis_size(self.sp_axis)
            pooled = lax.psum(jnp.sum(x, axis=1), self.sp_axis) / t_global
        else:
            pooled = jnp.mean(x, axis=1)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(pooled)


class SyntheticSeqDataset(COINNDataset):
    """Deterministic synthetic sequence-classification samples.

    Class signal is a low-frequency sinusoid mixed into white noise — linearly
    separable only through temporal context, so attention quality actually
    moves the metric.
    """

    def __getitem__(self, ix):
        _, file = self.indices[ix]
        t = int(self.cache.get("seq_len", 128))
        f = int(self.cache.get("num_features", 16))
        n_cls = int(self.cache.get("num_classes", 2))
        fid = stable_file_id(file)
        rng = np.random.default_rng(fid)
        y = fid % n_cls
        ts = np.arange(t)[:, None] / t
        signal = np.sin(2 * np.pi * (y + 1) * ts)
        x = (rng.normal(size=(t, f)) * 0.5 + signal).astype(np.float32)
        return {"inputs": x, "labels": np.int32(y)}


class SeqTrainer(COINNTrainer):
    """Trainer wiring for the sequence family (same contract as FSVTrainer).

    Implements ``iteration_sharded``, so the federated mesh transport can
    shard each site's sequences over an ``sp`` axis (ring attention inside
    ``MeshFederation``'s compiled round — ``cache['sequence_parallel']``,
    ``parallel/seq_mesh.py``) with the full trainer stack: optax update,
    metrics, checkpoints — one checkpoint format across sp values.
    """

    def _build_model(self, sp_axis=None, tp_axis=None):
        return SeqClassifier(
            num_classes=int(self.cache.get("num_classes", 2)),
            d_model=int(self.cache.get("d_model", 128)),
            num_heads=int(self.cache.get("num_heads", 4)),
            num_layers=int(self.cache.get("num_layers", 2)),
            max_len=int(self.cache.get("max_len", 4096)),
            causal=bool(self.cache.get("causal", False)),
            dtype=jnp.dtype(self.cache.setdefault("compute_dtype", "float32")),
            attn_impl=self.cache.get("attn_impl"),
            sp_axis=sp_axis,
            tp_axis=tp_axis,
        )

    def _init_nn_model(self):
        self.nn["seq_net"] = self._build_model()

    def _iteration_axis(self, params, batch, **axes):
        """Shared body of the axis-sharded iterations: same params, the
        model re-built with the given mesh axis bound (ring attention for
        ``sp_axis``, Megatron col/row slicing for ``tp_axis``); logits come
        out replicated across the intra axis."""
        model = self._build_model(**axes)
        logits = model.apply(params["seq_net"], batch["inputs"])
        return classification_outputs(
            logits, batch["labels"], mask=batch.get("_mask")
        )

    def iteration_sharded(self, params, batch, rng=None, sp_axis=None):
        if sp_axis is None:
            return self.iteration(params, batch, rng)
        return self._iteration_axis(params, batch, sp_axis=sp_axis)

    def iteration_tp(self, params, batch, rng=None, tp_axis=None):
        if tp_axis is None:
            return self.iteration(params, batch, rng)
        return self._iteration_axis(params, batch, tp_axis=tp_axis)

    def example_inputs(self):
        x = jnp.zeros(
            (1, int(self.cache.get("seq_len", 128)),
             int(self.cache.get("num_features", 16))),
            jnp.float32,
        )
        return {"seq_net": (x,)}

    def iteration(self, params, batch, rng=None):
        logits = self.nn["seq_net"].apply(params["seq_net"], batch["inputs"])
        return classification_outputs(logits, batch["labels"], mask=batch.get("_mask"))
