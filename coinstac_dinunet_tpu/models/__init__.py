"""Model families for the benchmark workloads (BASELINE.md configs).

The reference ships no model zoo — users subclass ``COINNTrainer`` and bring
torch modules (its two example repos wire FreeSurfer-MLP and VBM-3D-CNN
classifiers, ``README.md:30-33``).  This package provides TPU-first flax
equivalents for every benchmark config, each with a trainer subclass and a
synthetic dataset so the full federated stack can run and be measured without
private neuroimaging data:

- :mod:`.mlp` — FreeSurfer-volumes MLP classifier (configs 1-2).
- :mod:`.cnn3d` — VBM 3-D CNN classifier, the flagship (config 3).
- :mod:`.resnet` — ResNet-18 image classifier (config 4).
- :mod:`.multinet` — two-network scheme (config 5).

Design: channels-last layouts (NDHWC), GroupNorm rather than BatchNorm (pure
``apply`` — no mutable batch statistics to drift across federated sites),
optional bfloat16 compute with float32 params.
"""
from .cnn3d import (NiftiVBMDataset, SyntheticVBMDataset,  # noqa: F401
                    VBM3DNet, VBMTrainer, fit_volume)
from .mlp import FSVDataset, FSVNet, FSVTrainer  # noqa: F401
from .multinet import MultiNetTrainer  # noqa: F401
from .resnet import ResNet18, ResNetTrainer, SyntheticImageDataset  # noqa: F401
from .transformer import (  # noqa: F401
    SeqClassifier,
    SeqTrainer,
    SyntheticSeqDataset,
)

__all__ = [
    "FSVNet", "FSVTrainer", "FSVDataset",
    "VBM3DNet", "VBMTrainer", "SyntheticVBMDataset", "NiftiVBMDataset", "fit_volume",
    "ResNet18", "ResNetTrainer", "SyntheticImageDataset",
    "MultiNetTrainer",
    "SeqClassifier", "SeqTrainer", "SyntheticSeqDataset",
]
