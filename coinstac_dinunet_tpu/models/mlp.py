"""FreeSurfer-volumes MLP classifier (benchmark configs 1-2).

The reference's canonical first workload: an MLP over FreeSurfer regional
volume features (external example repo; see SURVEY §6 / BASELINE.md).
"""
import numpy as np

import flax.linen as nn
import jax.numpy as jnp

from ..data import COINNDataset
from ..metrics import classification_outputs
from ..trainer import COINNTrainer
from ..utils import stable_file_id


class FSVNet(nn.Module):
    """MLP over FreeSurfer volume features."""

    num_classes: int = 2
    hidden: tuple = (256, 128, 64)
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False, rng=None):
        x = jnp.asarray(x, self.dtype)
        for h in self.hidden:
            x = nn.Dense(h, dtype=self.dtype)(x)
            x = nn.relu(x)
            if train and self.dropout > 0 and rng is not None:
                x = nn.Dropout(self.dropout, deterministic=False)(x, rng=rng)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


class FSVDataset(COINNDataset):
    """Loads one-row-per-subject feature files.

    Default file format: a ``.npy``/``.csv`` per subject holding the feature
    vector, with the label encoded by the ``labels`` mapping in the data conf
    (or a synthetic deterministic sample when ``synthetic=True`` in cache —
    used by benches/tests)."""

    def __getitem__(self, ix):
        _, file = self.indices[ix]
        num_features = int(self.cache.get("input_size", 66))
        if self.cache.get("synthetic"):
            fid = stable_file_id(file)
            rng = np.random.default_rng(fid)
            y = fid % int(self.cache.get("num_classes", 2))
            x = rng.normal(loc=0.1 * y, size=num_features).astype(np.float32)
            return {"inputs": x, "labels": np.int32(y)}
        path = f"{self.path()}/{file}"
        x = (np.load(path) if str(file).endswith(".npy")
             else np.loadtxt(path, delimiter=",")).astype(np.float32)
        y = np.int32(self.data_conf.get("labels", {}).get(str(file), 0))
        return {"inputs": x.reshape(-1)[:num_features], "labels": y}


class FSVTrainer(COINNTrainer):
    def _init_nn_model(self):
        self.nn["fsv_net"] = FSVNet(
            num_classes=int(self.cache.get("num_classes", 2)),
            hidden=tuple(self.cache.get("hidden_sizes", (256, 128, 64))),
            dropout=float(self.cache.get("dropout", 0.1)),
            dtype=jnp.dtype(self.cache.setdefault("compute_dtype", "float32")),
        )

    def example_inputs(self):
        x = jnp.zeros((1, int(self.cache.get("input_size", 66))), jnp.float32)
        return {"fsv_net": (x,)}

    def iteration(self, params, batch, rng=None):
        logits = self.nn["fsv_net"].apply(
            params["fsv_net"], batch["inputs"], train=rng is not None, rng=rng
        )
        return classification_outputs(logits, batch["labels"], mask=batch.get("_mask"))
