from .loss import (
    binary_cross_entropy_with_logits,
    classification_outputs,
    cross_entropy,
    dice_loss_binary,
)
from .metrics import (
    AUCROCMetrics,
    COINNAverages,
    COINNMetrics,
    ConfusionMatrix,
    Prf1a,
    new_metrics,
)

__all__ = [
    "COINNMetrics",
    "COINNAverages",
    "Prf1a",
    "ConfusionMatrix",
    "AUCROCMetrics",
    "new_metrics",
    "dice_loss_binary",
    "cross_entropy",
    "binary_cross_entropy_with_logits",
    "classification_outputs",
]
