"""Loss functions (pure jnp; jit/vjp-safe).

Parity: reference ``metrics/loss.py:1-23`` (weighted binary dice).  Extended
with the standard classification losses the trainer/models need, all written
to fuse cleanly under XLA (no data-dependent shapes).
"""
import jax.numpy as jnp


def dice_loss_binary(pred, true, beta=1.0, eps=1e-5, mask=None):
    """Weighted binary dice loss in β-F-measure form.

    ``beta > 1`` weighs recall higher, ``beta < 1`` precision higher.
    ``mask`` zeroes out padded samples.
    """
    pred = pred.reshape(pred.shape[0], -1).astype(jnp.float32)
    true = true.reshape(true.shape[0], -1).astype(jnp.float32)
    if mask is not None:
        m = mask.reshape(-1, 1).astype(jnp.float32)
        pred, true = pred * m, true * m
    b2 = beta * beta
    tp = jnp.sum(pred * true)
    fp = jnp.sum(pred * (1 - true))
    fn = jnp.sum((1 - pred) * true)
    score = ((1 + b2) * tp + eps) / ((1 + b2) * tp + b2 * fn + fp + eps)
    return 1.0 - score


def cross_entropy(logits, labels, mask=None):
    """Mean softmax cross-entropy over integer labels, padding-masked."""
    import jax

    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    if mask is None:
        return jnp.mean(nll)
    m = mask.astype(jnp.float32)
    if m.shape == nll.shape:  # full per-element mask
        return jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)
    # (B,) loader mask: broadcast over segmentation-shaped (B, ...) nll
    m = m.reshape(m.shape[0], *([1] * (nll.ndim - 1)))
    denom = jnp.sum(m) * (nll.size / nll.shape[0])
    return jnp.sum(nll * m) / jnp.maximum(denom, 1.0)


def classification_outputs(logits, labels, mask=None):
    """Standard ``iteration`` return dict for a softmax classifier.

    Includes ``prob`` — the positive-class probability — for binary heads so
    probability-ranked metrics (:class:`..metrics.AUCROCMetrics`, ref
    ``metrics/metrics.py:292-329``) receive calibrated scores instead of
    argmax labels (AUC over hard 0/1 predictions collapses to accuracy).
    """
    import jax

    it = {
        "loss": cross_entropy(logits, labels, mask=mask),
        "pred": jnp.argmax(logits, -1),
        "true": labels,
    }
    if logits.shape[-1] == 2:
        it["prob"] = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)[..., 1]
    return it


def binary_cross_entropy_with_logits(logits, labels, mask=None):
    import jax

    logits = logits.astype(jnp.float32)
    labels = labels.astype(jnp.float32)
    per = jnp.maximum(logits, 0) - logits * labels + jax.nn.softplus(-jnp.abs(logits))
    if mask is None:
        return jnp.mean(per)
    m = mask.astype(jnp.float32).reshape(per.shape[0], *([1] * (per.ndim - 1)))
    return jnp.sum(per * m) / jnp.maximum(jnp.sum(m) * per[0].size, 1.0)
