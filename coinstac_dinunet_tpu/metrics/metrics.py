"""Metrics: pure-pytree accumulators with exact cross-site reduction.

Capability parity with the reference ``metrics/metrics.py:17-329``
(COINNMetrics ABC + COINNAverages/Prf1a/ConfusionMatrix/AUCROCMetrics), with a
TPU-first contract:

- Every metric's raw statistics live in a small fixed-shape numpy/jnp *state*
  pytree, so ``update_state`` can run **inside a jit-compiled train step**; the
  host object merely wraps the state for the reference-style OO API
  (``add/accumulate/reset/get/extract/serialize/reduce_sites``).
- Classification metrics' ``update_state`` take an optional per-sample
  ``mask`` so padded (lockstep) batches contribute nothing — padding is
  mandatory under XLA's static shapes, masking replaces the reference's padded
  sampler trick.  (``COINNAverages`` instead weighs by ``n`` — pass
  ``mask.sum()`` for padded batches.)
- ``serialize()`` ships **raw counts**, and ``reduce_sites`` merges counts
  before deriving scores — exact global P/R/F1 rather than the reference's
  mean-of-site-scores approximation (ref ``metrics/metrics.py:217-218,288-289``).
"""
import numpy as np

from .. import config

_EPS = config.metrics_eps


def _round(x):
    return round(float(x), config.metrics_num_precision)


class COINNMetrics:
    """Base contract every metric obeys.

    State-centric: subclasses define ``empty_state`` and pure ``update_state``;
    the instance holds a current state and exposes the host-side API.
    """

    monitor = None  # attribute name used for early-stopping extraction
    jit_safe = True  # False → state has data-dependent shapes; host-side only

    def __init__(self):
        self.state = self.empty_state()

    # ---- pure/functional API (jit-safe) ---------------------------------
    @staticmethod
    def empty_state():
        raise NotImplementedError

    @staticmethod
    def update_state(state, pred, true, mask=None):
        raise NotImplementedError

    @staticmethod
    def merge_states(a, b):
        """Default: states are addable count pytrees."""
        import jax

        return jax.tree_util.tree_map(lambda x, y: x + y, a, b)

    # ---- host-side OO API ------------------------------------------------
    def add(self, pred, true, mask=None):
        # compute the per-call delta on device, fold into the f64 accumulator
        self.update(self.update_state(self.empty_state(), pred, true, mask))

    def accumulate(self, other):
        if isinstance(other, COINNMetrics):
            other = other.state
        self.state = self.merge_states(self.state, other)
        return self

    def update(self, state):
        """Fold a state pytree produced inside a jitted step into this metric.

        Device states are f32 (per-batch counts, exact below 2^24); they are
        promoted to host numpy f64 here so the running totals stay exact.
        """
        import jax

        state = jax.tree_util.tree_map(
            lambda x: np.asarray(x, dtype=np.float64), state
        )
        self.state = self.merge_states(self.state, state)
        return self

    def reset(self):
        self.state = self.empty_state()

    def get(self):
        raise NotImplementedError

    def extract(self, name):
        return getattr(self, name)

    def serialize(self):
        """Raw-count payload for the wire (JSON-able)."""
        import jax

        return [np.asarray(l).tolist() for l in jax.tree_util.tree_leaves(self.state)]

    @classmethod
    def deserialize(cls, payload):
        import jax

        m = cls()
        leaves, treedef = jax.tree_util.tree_flatten(m.state)
        new = [np.asarray(p, dtype=np.asarray(l).dtype) for l, p in zip(leaves, payload)]
        m.state = jax.tree_util.tree_unflatten(treedef, new)
        return m

    @classmethod
    def reduce_sites(cls, site_payloads):
        """Merge N sites' serialized payloads exactly (count merge)."""
        merged = cls()
        for payload in site_payloads:
            merged.accumulate(cls.deserialize(payload))
        return merged

    def new(self):
        return type(self)()


class COINNAverages(COINNMetrics):
    """K simultaneous (sum, count) averages (e.g. per-loss-term tracking)."""

    monitor = "average"

    def __init__(self, num_averages=1):
        self.num_averages = int(num_averages)
        super().__init__()

    def empty_state(self):
        return {
            "sum": np.zeros(self.num_averages, dtype=np.float64),
            "count": np.zeros(self.num_averages, dtype=np.float64),
        }

    @staticmethod
    def update_state(state, values, n=1):
        """``values`` are per-batch aggregates; ``n`` is the weight — pass
        ``mask.sum()`` for padded batches to exclude padding."""
        import jax.numpy as jnp

        # float32 in the jit path (TPU-friendly); host-side merges stay f64
        values = jnp.atleast_1d(jnp.asarray(values, dtype=jnp.float32))
        n = jnp.asarray(n, dtype=jnp.float32)
        return {"sum": state["sum"] + values * n, "count": state["count"] + n * jnp.ones_like(values)}

    def add(self, values, n=1):
        self.update(self.update_state(self.empty_state(), values, n))

    def serialize(self):
        # explicit [sums, counts] payload order (wire contract)
        return [np.asarray(self.state["sum"]).tolist(), np.asarray(self.state["count"]).tolist()]

    @classmethod
    def deserialize(cls, payload):
        m = cls(num_averages=len(payload[0]))
        m.state = {
            "sum": np.asarray(payload[0], dtype=np.float64),
            "count": np.asarray(payload[1], dtype=np.float64),
        }
        return m

    @property
    def averages(self):
        s, c = np.asarray(self.state["sum"]), np.asarray(self.state["count"])
        return s / np.where(c == 0, 1.0, c)

    @property
    def average(self):
        return _round(self.averages[0])

    def get(self):
        return [_round(a) for a in self.averages]

    def new(self):
        return COINNAverages(self.num_averages)

    @classmethod
    def reduce_sites(cls, site_payloads):
        merged = None
        for payload in site_payloads:
            m = cls.deserialize(payload)
            merged = m if merged is None else merged.accumulate(m)
        return merged if merged is not None else cls()


class Prf1a(COINNMetrics):
    """Binary precision/recall/F1/accuracy/IoU from TP/FP/TN/FN counts."""

    monitor = "f1"

    @staticmethod
    def empty_state():
        return {"tp": np.float64(0), "fp": np.float64(0), "tn": np.float64(0), "fn": np.float64(0)}

    @staticmethod
    def update_state(state, pred, true, mask=None):
        # float32 inside jit: per-batch counts are < 2^24 so exact; fold each
        # batch's state into the host-side f64 accumulator for exact totals
        import jax.numpy as jnp

        pred = jnp.asarray(pred).reshape(-1).astype(jnp.float32)
        true = jnp.asarray(true).reshape(-1).astype(jnp.float32)
        w = jnp.ones_like(pred) if mask is None else jnp.asarray(mask).reshape(-1).astype(jnp.float32)
        tp = jnp.sum(w * pred * true)
        fp = jnp.sum(w * pred * (1 - true))
        fn = jnp.sum(w * (1 - pred) * true)
        tn = jnp.sum(w * (1 - pred) * (1 - true))
        return {
            "tp": state["tp"] + tp,
            "fp": state["fp"] + fp,
            "tn": state["tn"] + tn,
            "fn": state["fn"] + fn,
        }

    def _c(self, k):
        return float(np.asarray(self.state[k]))

    @property
    def precision(self):
        tp, fp = self._c("tp"), self._c("fp")
        return _round(tp / max(tp + fp, _EPS))

    @property
    def recall(self):
        tp, fn = self._c("tp"), self._c("fn")
        return _round(tp / max(tp + fn, _EPS))

    @property
    def f1(self):
        p, r = self.precision, self.recall
        return _round(2 * p * r / max(p + r, _EPS))

    @property
    def accuracy(self):
        tp, fp, tn, fn = (self._c(k) for k in ("tp", "fp", "tn", "fn"))
        return _round((tp + tn) / max(tp + fp + tn + fn, _EPS))

    @property
    def overlap(self):
        """Intersection-over-union of the positive class."""
        tp, fp, fn = self._c("tp"), self._c("fp"), self._c("fn")
        return _round(tp / max(tp + fp + fn, _EPS))

    def prfa(self):
        return [self.precision, self.recall, self.f1, self.accuracy]

    def get(self):
        return self.prfa()


class ConfusionMatrix(COINNMetrics):
    """Multi-class K×K confusion matrix with per-class and macro P/R/F1."""

    monitor = "f1"

    def __init__(self, num_classes=2):
        self.num_classes = int(num_classes)
        super().__init__()

    def empty_state(self):
        return {"matrix": np.zeros((self.num_classes, self.num_classes), dtype=np.float64)}

    @staticmethod
    def update_state(state, pred, true, mask=None):
        import jax.numpy as jnp

        k = state["matrix"].shape[0]
        pred = jnp.asarray(pred).reshape(-1).astype(jnp.int32)
        true = jnp.asarray(true).reshape(-1).astype(jnp.int32)
        w = (
            jnp.ones(pred.shape, dtype=jnp.float32)
            if mask is None
            else jnp.asarray(mask).reshape(-1).astype(jnp.float32)
        )
        # row = true class, col = predicted class; scatter-add via one flat bincount
        idx = true * k + pred
        counts = jnp.zeros(k * k, dtype=jnp.float32).at[idx].add(w)
        return {"matrix": state["matrix"] + counts.reshape(k, k)}

    @property
    def matrix(self):
        return np.asarray(self.state["matrix"])

    def _per_class(self):
        m = self.matrix
        tp = np.diag(m)
        fp = m.sum(axis=0) - tp  # predicted-as-c but not c
        fn = m.sum(axis=1) - tp  # is-c but predicted otherwise
        precision = tp / np.maximum(tp + fp, _EPS)
        recall = tp / np.maximum(tp + fn, _EPS)
        f1 = 2 * precision * recall / np.maximum(precision + recall, _EPS)
        return precision, recall, f1

    @property
    def precision(self):
        return _round(self._per_class()[0].mean())

    @property
    def recall(self):
        return _round(self._per_class()[1].mean())

    @property
    def f1(self):
        return _round(self._per_class()[2].mean())

    @property
    def accuracy(self):
        m = self.matrix
        return _round(np.diag(m).sum() / max(m.sum(), _EPS))

    def get(self):
        # same column order as Prf1a.get() so log headers stay valid when
        # new_metrics() swaps the metric class on num_classes
        return [self.precision, self.recall, self.f1, self.accuracy]

    def new(self):
        return ConfusionMatrix(self.num_classes)

    @classmethod
    def reduce_sites(cls, site_payloads):
        if not site_payloads:
            return cls()
        merged = None
        for payload in site_payloads:
            mat = np.asarray(payload[0], dtype=np.float64)
            m = cls(num_classes=mat.shape[0])
            m.state = {"matrix": mat}
            merged = m if merged is None else merged.accumulate(m)
        return merged


class AUCROCMetrics(COINNMetrics):
    """Binary AUC-ROC.  Accumulates (probability, label) pairs; the wire ships
    the raw pairs so the aggregator computes the *exact global* AUC (the
    reference averages per-site AUCs — an approximation)."""

    monitor = "auc"
    jit_safe = False  # accumulates variable-length prob/label lists

    @staticmethod
    def empty_state():
        return {"probs": np.zeros((0,), np.float64), "labels": np.zeros((0,), np.float64)}

    @staticmethod
    def update_state(state, probs, labels, mask=None):
        probs = np.asarray(probs, dtype=np.float64).reshape(-1)
        labels = np.asarray(labels, dtype=np.float64).reshape(-1)
        if mask is not None:
            keep = np.asarray(mask).reshape(-1) > 0
            probs, labels = probs[keep], labels[keep]
        return {
            "probs": np.concatenate([state["probs"], probs]),
            "labels": np.concatenate([state["labels"], labels]),
        }

    @staticmethod
    def merge_states(a, b):
        return {
            "probs": np.concatenate([np.asarray(a["probs"]), np.asarray(b["probs"])]),
            "labels": np.concatenate([np.asarray(a["labels"]), np.asarray(b["labels"])]),
        }

    @property
    def auc(self):
        probs, labels = self.state["probs"], self.state["labels"]
        n_pos = float((labels > 0.5).sum())
        n_neg = float(len(labels) - n_pos)
        if n_pos == 0 or n_neg == 0:
            return 0.0
        # rank-sum (Mann-Whitney) AUC with tie handling — no sklearn dependency
        order = np.argsort(probs, kind="mergesort")
        ranks = np.empty(len(probs), dtype=np.float64)
        sorted_p = probs[order]
        i = 0
        while i < len(sorted_p):
            j = i
            while j + 1 < len(sorted_p) and sorted_p[j + 1] == sorted_p[i]:
                j += 1
            ranks[order[i : j + 1]] = 0.5 * (i + j) + 1.0
            i = j + 1
        pos_rank_sum = ranks[labels > 0.5].sum()
        return _round((pos_rank_sum - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))

    def get(self):
        return [self.auc]


def new_metrics(num_classes=2, binary_as_auc=False):
    """Metric factory by task shape (≙ COINNTrainer.new_metrics)."""
    if num_classes <= 2:
        return AUCROCMetrics() if binary_as_auc else Prf1a()
    return ConfusionMatrix(num_classes)
