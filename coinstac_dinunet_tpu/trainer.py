"""COINNTrainer — federated specialization of the NN runtime.

Capability parity with the reference ``trainer.py:15-80``: federated
best-model broadcast during pretrain (writes into ``transferDirectory``),
distributed validation/test producing serialized wire payloads, and a metric
factory keyed by task shape.
"""
import os
import shutil

from . import config
from .config.keys import Key, Mode
from .metrics import new_metrics as _metric_factory
from .nn.basetrainer import NNTrainer
from .telemetry import get_active as _telemetry
from .telemetry import health as _health
from .telemetry import perf as _perf
from .utils.utils import performance_improved_


class COINNTrainer(NNTrainer):
    """Trainer used by site nodes in a federated run."""

    def _save_if_better(self, epoch, score):
        """During pretrain, an improved model is written into the transfer
        directory so the aggregator can broadcast it to every site."""
        if performance_improved_(epoch, score, self.cache):
            out = os.path.join(
                self.state.get("transferDirectory", "."),
                self.cache.get("best_nn_state", config.weights_file),
            )
            self.save_checkpoint(full_path=out)
            self.cache["weights_file"] = os.path.basename(out)
            return True
        return False

    def _on_validation_end(self, epoch, averages, metrics):
        if self.cache.get("pretrain"):
            monitor = self.cache.get("monitor_metric", "f1")
            try:
                score = metrics.extract(monitor)
            except AttributeError:
                score = averages.average
            self._save_if_better(epoch, score)
        else:
            super()._on_validation_end(epoch, averages, metrics)

    # ------------------------------------------------ distributed eval / test
    def validation_distributed(self):
        """Run local validation and emit the serialized payload the
        aggregator reduces across sites (exact count merge)."""
        rec = _telemetry()
        with rec.span("local:validation", cat="eval"):
            averages, metrics = self.evaluation(
                Mode.VALIDATION, [self.data_handle.get_validation_dataset()]
            )
        if rec.enabled:
            # the site's own monitored-metric trajectory (the stall
            # detector's series; the aggregator records the GLOBAL one)
            try:
                score = metrics.extract(self.cache.get("monitor_metric", "f1"))
            except AttributeError:
                score = averages.average
            _health.record_val_score(self.cache, score, recorder=rec)
            # eval allocates its own buffers: a memory sample here catches
            # validation-phase growth the train-round samples would miss.
            # leak_watch=False: this out-of-cadence spike must not reset
            # the leak detector's train-round growth streak
            _perf.sample_device_memory(self.cache, recorder=rec,
                                       leak_watch=False)
        return {
            Key.VALIDATION_SERIALIZABLE.value: [
                {"averages": averages.serialize(), "metrics": metrics.serialize()}
            ]
        }

    def test_distributed(self):
        """Reload the fold's best checkpoint, then test (ref ``trainer.py:52``)."""
        best = self.cache.get("best_nn_state", "best.ckpt")
        best_path = self.checkpoint_path(best)
        if os.path.exists(best_path):
            self.load_checkpoint(name=best)
        ds = self.data_handle.get_test_dataset(load_sparse=bool(self.cache.get("load_sparse")))
        with _telemetry().span("local:test", cat="eval"):
            averages, metrics = self.evaluation(
                Mode.TEST,
                ds if isinstance(ds, list) else [ds],
                save_pred=bool(self.cache.get("save_predictions")),
            )
        return {
            Key.TEST_SERIALIZABLE.value: [
                {"averages": averages.serialize(), "metrics": metrics.serialize()}
            ]
        }

    def load_broadcast_weights(self):
        """Adopt the pretrained weights the aggregator broadcast."""
        fname = self.input.get("weights_file", self.cache.get("weights_file"))
        if not fname:
            return False
        path = os.path.join(self.state.get("baseDirectory", "."), fname)
        if os.path.exists(path):
            # broadcast file — framework msgpack only, never torch pickles
            self.load_checkpoint(full_path=path, load_optimizer=False,
                                 allow_torch=False)
            # keep a local copy as the fold's current best
            shutil.copy(path, self.checkpoint_path(self.cache.get("best_nn_state", "best.ckpt")))
            return True
        return False

    def new_metrics(self):
        """Factory by task shape (ref ``trainer.py:71-80``): binary →
        Prf1a (or AUC when ``monitor_metric == 'auc'``), multi-class →
        ConfusionMatrix."""
        num_classes = int(self.cache.get("num_classes", 2))
        as_auc = self.cache.get("monitor_metric") == "auc"
        return _metric_factory(num_classes, binary_as_auc=as_auc)
