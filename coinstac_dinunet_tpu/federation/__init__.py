"""federation/ — the mega-federation scale layer (10³–10⁴ simulated sites).

The paper's engine model invokes sites serially and its aggregator loads
every site payload at once — both walls at production scale.  This package
is the scale inversion (ROADMAP item 1):

- :mod:`.vector` — :class:`SiteVectorizedFederation`: B simulated sites'
  local steps + the cross-site reduce as ONE jit, the stacked site
  dimension on ``MeshAxis.SITE`` (vmap per device block, ``shard_map``
  across blocks — the Podracer/Anakin shape, PAPERS.md arXiv:2104.06272).
  Params stay shared; opt/rng/step stack per site.
- :mod:`.engine` — :class:`SiteVectorizedEngine`: the full MeshEngine
  lifecycle over that plane, with chaos invoke faults + the
  ``site_quorum`` dropout contract restored at the per-site round
  boundary.
- the file-wire side lives in :mod:`~..parallel.reducer`: the k-ary
  hierarchical tree-reduce (``cache['reduce_fanin']``) streams the
  aggregator fan-in through the atomic transport instead of
  materializing all ``n_sites`` payloads.
- :mod:`.daemon` — :class:`DaemonEngine`: the fresh-process deployment
  without its per-invocation cold start — one long-lived warm worker
  process per site (+ aggregator) over a framed JSON pipe, supervised
  restarts (``worker:restart``) instead of dead sites, the node scripts
  and the cache/input/state contract untouched.
- :mod:`.membership` — :class:`MembershipRoster` + the aggregator-side
  elastic-membership rounds (ISSUE 15): the versioned roster epoch, the
  mid-run JOIN admission handshake, graceful LEAVE retirement, and
  rejoin-after-death with stale incarnations refused by epoch.

Benchmark: ``scripts/bench_federation.py`` (headline: rounds/sec at 1,000
simulated sites, ledgered for ``telemetry doctor`` regression verdicts).
See docs/FEDERATION.md for the operator guide.
"""
from .daemon import DaemonEngine  # noqa: F401
from .engine import SiteVectorizedEngine  # noqa: F401
from .membership import MembershipRoster  # noqa: F401
from .vector import SiteVectorizedFederation, resolve_site_shards  # noqa: F401

__all__ = [
    "DaemonEngine",
    "MembershipRoster",
    "SiteVectorizedEngine",
    "SiteVectorizedFederation",
    "resolve_site_shards",
]
