"""Site-vectorized federation — thousands of simulated sites as ONE jit.

The serial engine transport invokes every site one at a time (one jit
dispatch + one wire payload per site per round), and even the mesh
transport (:mod:`~..parallel.mesh`) needs a physical device rank per site —
neither survives the ROADMAP's 10³–10⁴-site production regime.  This module
applies the Podracer/Anakin batching shape (PAPERS.md arXiv:2104.06272):
**many logically-independent site workers vectorized under one compiled
step**, with the stacked site dimension living on the ``MeshAxis.SITE``
axis and optionally sharded across the host's devices via ``shard_map``.

State layout (the site-vectorization memory contract):

- ``params`` — UNTOUCHED, one shared copy: dSGD's identical init +
  identical averaged update keeps every site's parameters bitwise equal
  (the replication invariant of ``parallel/mesh.py``), so stacking them
  B× would buy nothing and cost everything at scale.
- ``opt_state`` / ``rng`` / ``step`` — stacked along a leading
  ``MeshAxis.SITE`` axis: each simulated site carries its own optimizer
  state, carried rng stream, and step counter, so per-site divergence
  (future capacity weighting, per-site schedules) has a place to live.
  Under dSGD they advance in lockstep on the same averaged gradients,
  which keeps the stack replicated-by-construction — the invariant
  :meth:`SiteVectorizedFederation.train_step` relies on when it applies
  row 0's update to the shared params and when resume rebuilds the stack
  by tiling the trainer's state.
- metrics / averages / participation weights — per-site inside the step,
  reduced exactly like the mesh transport (psum ≙ axis-0 sum).

The cross-site gradient average inside the step is a 2-level hierarchical
reduce when the site axis is device-sharded: weighted partial sums within
each device's site block, one ``psum`` across the ``site`` axis, a single
normalization — the in-jit mirror of the file-wire tree-reduce in
:mod:`~..parallel.reducer`.

Semantics match :class:`~..parallel.mesh.MeshFederation` exactly where the
math is shared: same per-site forward-rng derivation
(``fold_in(split(carried)[1], site_index)`` — both split halves consumed,
per dinulint ``num-prng-discard``), same identically-advancing carried rng,
same participation weighting (a fully-masked site contributes nothing and
leaves the denominator), same aux reduction — so the vectorized engine's
score trajectory equals the file/mesh transports' on the same data + seed
(``tests/test_federation.py``).
"""
import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config.keys import Federation, MeshAxis
from ..nn.basetrainer import TrainState
from ..parallel.mesh import build_site_only_mesh
from ..telemetry import NULL_RECORDER
from ..telemetry import perf as _perf
from ..utils.jax_compat import resolve_donate_argnums, shard_map


def resolve_site_shards(n_sites, requested=None, devices=None):
    """Device count the stacked SITE axis shards over: the explicit request
    (``Federation.SITE_SHARDS``), else every local device when it divides
    ``n_sites`` evenly, else 1 (pure vmap on one device)."""
    n_dev = len(devices) if devices is not None else jax.device_count()
    if requested:
        requested = int(requested)
        if n_sites % requested:
            raise ValueError(
                f"site_shards={requested} must divide n_sites={n_sites} "
                "(the stacked site axis shards evenly or not at all)"
            )
        return requested
    return n_dev if (n_dev > 1 and n_sites % n_dev == 0) else 1


class SiteVectorizedFederation:
    """B simulated sites' local steps + the cross-site reduce as one jit.

    Drop-in for :class:`~..parallel.mesh.MeshFederation`'s transport
    interface (``train_step`` / ``eval_step`` / ``serialize_comm_state`` /
    ``restore_comm_state``), with no device-count ceiling on ``n_sites``.
    """

    SUPPORTED_ENGINES = ("dSGD",)

    def __init__(self, trainer, n_sites, agg_engine="dSGD", devices=None,
                 site_shards=None):
        self.trainer = trainer
        self.n_sites = int(n_sites)
        self.agg_engine = str(agg_engine)
        if self.agg_engine not in self.SUPPORTED_ENGINES:
            raise ValueError(
                f"agg_engine {self.agg_engine!r} is not supported on the "
                f"site-vectorized transport (supported: "
                f"{self.SUPPORTED_ENGINES}); use MeshFederation or the "
                "engine transport — refusing to silently change the "
                "algorithm"
            )
        if site_shards is None and trainer is not None:
            site_shards = trainer.cache.get(Federation.SITE_SHARDS)
        self.shards = resolve_site_shards(self.n_sites, site_shards, devices)
        self.mesh = (build_site_only_mesh(self.shards, devices)
                     if self.shards > 1 else None)
        self._site_ix = jnp.arange(self.n_sites, dtype=jnp.int32)
        self._site_state = None  # stacked {"opt", "rng", "step"}
        self._step = None
        self._eval = None
        self.rounds_done = 0
        # perf flight recorder sink — the engine binds its own lane here
        # (federation/engine.py); the null singleton keeps every perf
        # branch a single attribute test otherwise
        self.recorder = NULL_RECORDER

    # ---------------------------------------------------------- site stacking
    def _stacked_site_state(self):
        """Tile the trainer's (replicated-by-construction) opt/rng/step into
        the leading-SITE-axis stack every simulated site advances."""
        ts = self.trainer.train_state

        def tile(x):
            x = jnp.asarray(x)
            return jnp.tile(x[None], (self.n_sites,) + (1,) * x.ndim)

        return jax.tree_util.tree_map(
            tile, {"opt": ts.opt_state, "rng": ts.rng, "step": ts.step}
        )

    def _place(self, tree, spec):
        if self.mesh is None:
            return tree
        sharding = NamedSharding(self.mesh, spec)
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sharding), tree
        )

    def stack_site_batches(self, per_site_batches):
        """[site → list of k micro-batches] → pytree with leading (site, k)
        axes, site-sharded across the shards when the mesh is live."""
        stacked = [self.trainer._stack_batches(b) for b in per_site_batches]
        glob = {k: jnp.stack([s[k] for s in stacked]) for k in stacked[0]}
        return self._place(glob, P(MeshAxis.SITE))

    # ------------------------------------------------------------ train step
    def _build_step(self):
        trainer = self.trainer
        metrics_shell, averages_shell = trainer._metrics_shell()
        n_sites = self.n_sites
        sharded = self.mesh is not None

        # the whole federated round for a block of sites: vmapped local
        # steps, hierarchical weighted reduce, per-site optimizer advance
        def one_site(params, rng, step, six, batch):
            # per-site decorrelated forward rng; the carried rng advances
            # identically at every site (mesh-transport parity).  Both
            # split halves are consumed: [0] carries — bit-identical to
            # the historical split(rng)[0] — and [1] seeds the site stream
            next_rng, site_rng = jax.random.split(rng)
            ts = TrainState(params=params, opt_state=None, step=step,
                            rng=jax.random.fold_in(site_rng, six))
            grads, aux = trainer._grads_uncompiled(
                ts, batch, metrics_shell, averages_shell
            )
            mask = batch.get("_mask")
            w = ((jnp.sum(jnp.asarray(mask, jnp.float32)) > 0)
                 .astype(jnp.float32) if mask is not None else jnp.float32(1))
            aux = dict(aux)
            aux["rng"] = next_rng
            return grads, aux, w

        def block(params, site_state, site_ix, stacked):
            grads, aux, w = jax.vmap(
                one_site, in_axes=(None, 0, 0, 0, 0)
            )(params, site_state["rng"], site_state["step"], site_ix, stacked)
            # hierarchical reduce: weighted partial sums within this
            # device's site block, one psum across the SITE shards, one
            # normalization — the in-jit 2-level tree
            wpart = jnp.sum(w)
            gpart = jax.tree_util.tree_map(
                lambda g: jnp.tensordot(w, g, axes=(0, 0)), grads
            )
            if sharded:
                wsum = jax.lax.psum(wpart, MeshAxis.SITE)
                gpart = jax.tree_util.tree_map(
                    lambda g: jax.lax.psum(g, MeshAxis.SITE), gpart
                )
            else:
                wsum = wpart
            denom = jnp.maximum(wsum, 1.0)
            avg = jax.tree_util.tree_map(lambda g: g / denom, gpart)

            # per-site apply: every stacked optimizer state advances on the
            # SAME averaged gradients (replicated-by-construction), and the
            # shared params take row 0's update
            def site_update(opt_state):
                upd, new_opt = {}, {}
                for name in params:
                    upd[name], new_opt[name] = trainer.optimizer[name].update(
                        avg[name], opt_state[name], params[name]
                    )
                return upd, new_opt
            upds, new_opt = jax.vmap(site_update)(site_state["opt"])
            first = jax.tree_util.tree_map(lambda u: u[0], upds)
            new_params = {
                name: optax.apply_updates(params[name], first[name])
                for name in params
            }
            new_site = {"opt": new_opt, "rng": aux.pop("rng"),
                        "step": site_state["step"] + 1}

            # aux reduction (mesh parity: psum over sites ≙ axis-0 sum)
            def site_sum(x):
                x = jnp.sum(x, axis=0)
                return jax.lax.psum(x, MeshAxis.SITE) if sharded else x

            if aux.get("metrics") is not None:
                aux["metrics"] = jax.tree_util.tree_map(
                    site_sum, aux["metrics"]
                )
            aux["averages"] = jax.tree_util.tree_map(
                site_sum, aux["averages"]
            )
            aux["loss"] = site_sum(aux["loss"]) / n_sites
            if "host_scores" in aux:
                def gather(x):  # (S_local, k, B, ...) → (S·k, B, ...)
                    x = x.reshape((-1,) + x.shape[2:])
                    return (jax.lax.all_gather(
                        x, MeshAxis.SITE, axis=0, tiled=True
                    ) if sharded else x)
                aux["host_scores"] = jax.tree_util.tree_map(
                    gather, aux["host_scores"]
                )
            aux["rng"] = new_site["rng"][0]
            return new_params, new_site, aux

        # Donate the shared params AND the stacked per-site opt/rng/step:
        # both are returned as successors every round, so donation reuses
        # their buffers in place.  Without it the stacked optimizer state —
        # the one tree that scales with n_sites (B × opt-state bytes) —
        # keeps two generations live across every round (HBM peak doubles
        # at 10³–10⁴ sites).  Gated by cache['donate_buffers'] like the
        # trainer/mesh jits; enforced by dinulint tier-3's perf-donation
        # rule via the 'fed-vector-step*' entries.
        donate = resolve_donate_argnums(self.trainer.cache, (0, 1))
        if not sharded:
            return jax.jit(block, donate_argnums=donate)
        site_spec = P(MeshAxis.SITE)
        return jax.jit(shard_map(
            block, mesh=self.mesh,
            in_specs=(P(), site_spec, site_spec, site_spec),
            out_specs=(P(), site_spec, P()),
            check_vma=False,
        ), donate_argnums=donate)

    def train_step(self, site_batches):
        """One federated round for every simulated site — a single compiled
        call over the stacked site axis.  With the engine's recorder bound
        (``self.recorder``), the build records its XLA cost (``jit_cost``
        for the WHOLE B-site round) and every step records fenced wall
        time → the ``samples_per_sec``/``achieved_tflops``/``mfu`` series
        cover the mega-federation path."""
        if self._site_state is None:
            self._site_state = self._place(
                self._stacked_site_state(), P(MeshAxis.SITE)
            )
        rec = self.recorder
        stacked = (self.stack_site_batches(site_batches)
                   if isinstance(site_batches, (list, tuple))
                   else site_batches)
        built = self._step is None
        if built:
            self._step = self._build_step()
            if rec.enabled:
                _perf.record_jit_cost(
                    self.trainer.cache, "fed_step", self._step,
                    (self.trainer.train_state.params, self._site_state,
                     self._site_ix, stacked),
                    recorder=rec,
                )
        timer = _perf.StepTimer() if rec.enabled else None
        new_params, self._site_state, aux = self._step(
            self.trainer.train_state.params, self._site_state,
            self._site_ix, stacked,
        )
        if timer is not None and not built:
            # fenced wall time — skipped on the build round, whose wall
            # time is XLA compile, not a step (jit_cost marks the build)
            jax.block_until_ready(aux["loss"])
            leaf = jax.tree_util.tree_leaves(stacked)[0]
            timer.done(
                self.trainer.cache, "fed_step",
                int(leaf.shape[0]) * int(leaf.shape[1]) * int(leaf.shape[2]),
                recorder=rec,
            )
        # keep the trainer's single-site view current (checkpoints, eval):
        # row 0 IS the shared state under the replication invariant
        site = self._site_state
        self.trainer.train_state = self.trainer.train_state.replace(
            params=new_params,
            opt_state=jax.tree_util.tree_map(lambda x: x[0], site["opt"]),
            step=site["step"][0],
            rng=site["rng"][0],
        )
        self.rounds_done += 1
        return aux

    # ------------------------------------------------------------- evaluation
    def _build_eval(self):
        trainer = self.trainer
        metrics_shell, averages_shell = trainer._metrics_shell()
        sharded = self.mesh is not None

        def one_site(params, batch):
            it = trainer.iteration(params, batch, None)
            m_state, a_state = trainer._step_outputs(
                it, batch, metrics_shell, averages_shell
            )
            hs = None
            if m_state is None and not getattr(metrics_shell, "jit_safe", True):
                hs = trainer.host_scores_payload(it, batch)
            return m_state, a_state, hs

        def block(params, stacked):
            m, a, hs = jax.vmap(one_site, in_axes=(None, 0))(params, stacked)

            def site_sum(x):
                x = jnp.sum(x, axis=0)
                return jax.lax.psum(x, MeshAxis.SITE) if sharded else x

            if m is not None:
                m = jax.tree_util.tree_map(site_sum, m)
            a = jax.tree_util.tree_map(site_sum, a)
            if hs is not None:
                def gather(x):  # (S_local, B, ...) → (S·B, ...)
                    x = x.reshape((-1,) + x.shape[2:])
                    return (jax.lax.all_gather(
                        x, MeshAxis.SITE, axis=0, tiled=True
                    ) if sharded else x)
                hs = jax.tree_util.tree_map(gather, hs)
            return m, a, hs

        if not sharded:
            return jax.jit(block)
        return jax.jit(shard_map(
            block, mesh=self.mesh,
            in_specs=(P(), P(MeshAxis.SITE)),
            out_specs=(P(), P(), P()),
            check_vma=False,
        ))

    def eval_step(self, site_batches):
        """Globally-reduced evaluation over one batch per site; same return
        contract as :meth:`~..parallel.mesh.MeshFederation.eval_step`."""
        if isinstance(site_batches, (list, tuple)):
            # staging-time input cast (nn/basetrainer.py::_input_cast_dtype):
            # cast on the host BEFORE stacking/transfer so the compiled eval
            # consumes the compute dtype directly — the train path
            # (stack_site_batches → _stack_batches) already does this
            site_batches = [
                self.trainer._cast_batch_inputs(b) for b in site_batches
            ]
            glob = {
                k: jnp.stack([jnp.asarray(b[k]) for b in site_batches])
                for k in site_batches[0]
            }
        else:
            glob = site_batches
        glob = self._place(glob, P(MeshAxis.SITE))
        if self._eval is None:
            self._eval = self._build_eval()
        return self._eval(self.trainer.train_state.params, glob)

    # ----------------------------------------------------------------- resume
    def serialize_comm_state(self):
        """The stacked opt/rng/step need no payload: they are replicated-by-
        construction tiles of the trainer's checkpointed state, rebuilt on
        restore.  Only the round counter is carried."""
        return {"rounds_done": int(self.rounds_done)}

    def restore_comm_state(self, payload):
        self.rounds_done = int(payload.get("rounds_done", 0))
        # the trainer's state was just reloaded: re-tile lazily on next step
        self._site_state = None
