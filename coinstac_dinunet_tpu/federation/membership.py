"""Elastic membership — the aggregator-owned roster epoch machinery.

The reference federation contract fixes the site roster at INIT: death
(quorum drop) is the only exit and there is no entry path at all — fatal
for the ROADMAP's "millions of users" north star, where sites come and go
continuously (serverless/preemptible economics, PAPERS.md
arXiv:2509.14920).  This module converts the quorum/survivor-weighting and
``reappear`` machinery of PRs 9–14 into a first-class membership protocol:

- **Roster epoch.**  The aggregator owns a versioned membership record
  (``cache['roster']`` — :class:`MembershipRoster`), broadcast on the wire
  as :attr:`~..config.keys.RemoteWire.ROSTER_EPOCH` alongside
  ``wire_round`` and echoed back verbatim by every site.  Every
  join/leave/rejoin bumps the epoch.  ``cache['all_sites']`` mirrors the
  CURRENT member list, so the quorum policy
  (:meth:`~..nodes.remote.COINNRemote._check_quorum`) is always judged
  against the live roster, never the INIT one.
- **JOIN mid-run.**  The engine queues an admission request
  (``cache['membership_requests']``) carrying the donor's round-alignment
  sync (cursor/epoch/mode); the aggregator admits the joiner at the top of
  its next COMPUTATION round (epoch bump) and broadcasts an **admission
  record** (:attr:`~..config.keys.RemoteWire.ADMISSIONS`): the current
  fold assignment + ``target_batches`` + the sync + the admission epoch.
  The joiner's first invocation enters directly at the steady-state
  COMPUTATION phase (``nodes/local.py`` join entry) and warm-starts from
  the donor's live weights relayed through the existing pretrain-broadcast
  path — so a joiner admitted at round r contributes to round r+1's
  reduce, exactly once.
- **LEAVE gracefully.**  A leaving site flags its final contribution
  :attr:`~..config.keys.LocalWire.LEAVING`; the reducer counts the payload
  and the aggregator then retires the site (epoch bump) — never a
  ``site_died``, never a retry cycle.
- **Rejoin after death.**  The ``reappear`` chaos fault's scenario —
  a dropped site coming back — upgrades from a refused anomaly to a
  re-admission path: the engine re-admits the site with a FRESH cache
  through the same join handshake, and any payload out of the previous,
  dead incarnation is refused **by roster epoch** exactly as ``wire_round``
  refuses stale rounds (it echoes an epoch older than the site's current
  admission).

The tier-4 model checker's ``join``/``leave`` actions
(:mod:`~..analysis.model_check`) verify the roster-soundness invariants
(no contribution from a non-member epoch, quorum against the current
roster, joiner exactly-once admission); :func:`~..resilience.chaos
.churn_plan` drives the "churn 10% of 2,000 sites per round" drills.
"""
from .. import telemetry
from ..config.keys import LocalWire, Membership, RemoteWire
from ..utils import logger


class MembershipRoster:
    """The aggregator's versioned membership record (JSON-able; lives in
    ``cache['roster']`` and round-trips like every other protocol state).

    ``members`` maps each current member to the roster epoch it was
    (last) admitted at — the refusal boundary for payloads out of a
    previous incarnation.  ``left`` records graceful retirements (a left
    site may later rejoin, which re-admits it at a fresh epoch).
    """

    def __init__(self, epoch=1, members=None, left=None, joining=None,
                 pending=None):
        self.epoch = int(epoch)
        self.members = dict(members or {})
        self.left = list(left or [])
        # members admitted whose FIRST contribution has not arrived yet
        # (a join takes effect on the wire one round after admission): the
        # quorum check must not count them as dropped in the interim
        self.joining = list(joining or [])
        # the admission record broadcast for each still-joining member,
        # kept until its first contribution arrives so a retried (or
        # crashed-and-healed) aggregator attempt re-broadcasts the SAME
        # record instead of losing the admission with the drained request
        # queue — the exactly-once contract must survive the retry policy
        self.pending = dict(pending or {})

    # ------------------------------------------------------------ lifecycle
    @classmethod
    def load(cls, cache, seed_sites=None):
        """The roster from ``cache['roster']``; when absent, seeded from
        ``seed_sites`` (or ``cache['all_sites']``) at epoch 1 — every
        founding member's admitted epoch is 1.  Returns None when there is
        nothing to seed from yet (a standalone INIT round resolves it)."""
        rec = cache.get(Membership.ROSTER)
        if isinstance(rec, dict) and "members" in rec:
            return cls(rec.get("epoch", 1), rec.get("members"),
                       rec.get("left"), rec.get("joining"),
                       rec.get("pending"))
        sites = seed_sites if seed_sites is not None else cache.get("all_sites")
        if not sites:
            return None
        return cls(1, {str(s): 1 for s in sites}, [])

    def save(self, cache):
        """Commit the record AND mirror the current member list into
        ``cache['all_sites']`` — the single roster every quorum decision
        reads, so membership changes re-scope quorum immediately."""
        cache[Membership.ROSTER] = {
            "epoch": self.epoch,
            "members": dict(self.members),
            "left": list(self.left),
            "joining": list(self.joining),
            "pending": dict(self.pending),
        }
        cache["all_sites"] = sorted(self.members)

    # ------------------------------------------------------------ transitions
    def admit(self, site):
        """Join/rejoin: bump the epoch and (re-)admit ``site`` at it.  The
        joiner sits in the ``joining`` grace set until its first accepted
        contribution arrives — absent from a round's input, it is not yet
        *dropped* (the join takes effect on the wire one round later)."""
        site = str(site)
        self.epoch += 1
        self.members[site] = self.epoch
        if site in self.left:
            self.left.remove(site)
        if site not in self.joining:
            self.joining.append(site)
        return self.epoch

    def retire(self, site):
        """Graceful leave: bump the epoch and remove ``site``."""
        site = str(site)
        self.epoch += 1
        self.members.pop(site, None)
        if site in self.joining:
            self.joining.remove(site)
        self.pending.pop(site, None)
        if site not in self.left:
            self.left.append(site)
        return self.epoch

    # --------------------------------------------------------------- queries
    def is_member(self, site):
        return str(site) in self.members

    def admitted_epoch(self, site):
        return self.members.get(str(site))

    def refuses(self, site, echoed_epoch):
        """True when a payload must be refused by roster epoch: it came
        from a non-member, or it echoes an epoch OLDER than the site's
        current admission (a redelivery out of a previous incarnation).
        ``None`` echoes from members are tolerated — pre-ROSTER_EPOCH
        peers and the round before the first broadcast reaches a site."""
        site = str(site)
        if site not in self.members:
            return True
        if echoed_epoch is None:
            return False
        return int(echoed_epoch) < int(self.members[site])

    def quorum_need(self, quorum):
        """Minimum alive-member count under ``quorum``, judged against the
        CURRENT roster size — the one canonical normalization
        (:meth:`~..nodes.remote.COINNRemote._quorum_need`) over the live
        member list, so the live quorum evidence can never drift from the
        aggregator's actual quorum decision."""
        from ..nodes.remote import COINNRemote

        return COINNRemote._quorum_need(quorum, len(self.members))


# ------------------------------------------------------- aggregator rounds
def filter_membership(cache, input_dict):
    """The aggregator's roster-epoch gate, run BEFORE the quorum check and
    before any reducer/trainer snapshots ``input`` (the same ordering the
    ``proto-model-stale-contribution`` fix pinned for quorum filtering):
    drops every payload the roster refuses — non-member outputs and echoes
    older than the site's current admission — and returns
    ``(filtered_input, refused {site: reason})``.

    A refused payload is a protocol event, not a run failure: the fresh
    members' round proceeds survivor-weighted exactly as if the stale
    message had never arrived (`membership:refused` lands on the timeline
    for the postmortem)."""
    roster = MembershipRoster.load(cache)
    if roster is None:
        return input_dict, {}
    refused = {}
    for site, site_vars in input_dict.items():
        if not isinstance(site_vars, dict):
            continue
        echo = site_vars.get(LocalWire.ROSTER_EPOCH.value)
        if roster.refuses(site, echo):
            if roster.is_member(site):
                refused[site] = (
                    f"echoed roster epoch {echo} predates the site's "
                    f"admission at epoch {roster.admitted_epoch(site)}"
                )
            elif (
                site in roster.left
                and site_vars.get(LocalWire.LEAVING.value)
                and site_vars.get(LocalWire.ROUND.value) is not None
                and cache.get("wire_round") is not None
                and int(site_vars[LocalWire.ROUND.value])
                == int(cache["wire_round"])
            ):
                # the IN-FLIGHT round's flagged final contribution seen
                # again by a RETRIED aggregator attempt (the first attempt
                # retired the leaver, then failed before committing): the
                # protocol promises this payload counts, so the exact
                # current-round echo readmits it — any later redelivery
                # echoes the retirement round, lags `wire_round`, and is
                # refused here as before
                continue
            else:
                refused[site] = "not a roster member"
    # a joiner's first ACCEPTED contribution ends its joining grace: from
    # now on its absence counts as a drop like any member's, and the
    # retry-safety admission record kept for re-broadcast is retired
    arrived = [
        s for s in roster.joining if s in input_dict and s not in refused
    ]
    if arrived:
        for s in arrived:
            roster.joining.remove(s)
            roster.pending.pop(s, None)
        roster.save(cache)
    if not refused:
        return input_dict, {}
    rec = telemetry.get_active()
    for site, why in sorted(refused.items()):
        rec.event(
            Membership.EVENT_REFUSED, cat="membership", site=site,
            reason=why, epoch=roster.epoch,
        )
    logger.warn(
        f"membership: refused payloads by roster epoch from "
        f"{sorted(refused)} ({roster.epoch=}); the round proceeds with "
        "the current members"
    )
    return {k: v for k, v in input_dict.items() if k not in refused}, refused


def process_admissions(cache):
    """Drain the engine's join/rejoin request queue
    (``cache['membership_requests']``) into admission records: one epoch
    bump + one :attr:`~..config.keys.RemoteWire.ADMISSIONS` entry per
    joiner, carrying the current fold assignment, ``target_batches``, the
    donor round-alignment sync the engine attached, and the admission
    epoch.  A re-admitted site is also cleared from ``dropped_sites`` —
    its previous incarnation's drop no longer applies to the fresh one.

    Also returns (and re-broadcasts) the admission records of every
    still-joining member whose first contribution has not arrived yet: a
    failed aggregator attempt discards its output AFTER this step already
    drained the queue and mutated the roster, so the healed retry must be
    able to rebuild the identical broadcast from the roster's ``pending``
    records — same epoch, no second admission — or the join is silently
    lost (the engine-side activation is idempotent: it pops its awaiting
    entry once, so a re-broadcast is harmless).

    Returns the admissions dict to broadcast ({} when nothing is joining)."""
    requests = cache.pop(Membership.REQUESTS, None) or []
    roster = MembershipRoster.load(cache)
    if roster is None:
        # pre-INIT: nothing to admit into yet; the engine re-queues
        if requests:
            cache[Membership.REQUESTS] = requests
        return {}
    if not requests:
        return dict(roster.pending)
    rec = telemetry.get_active()
    admissions = {}
    for req in requests:
        site = str(req.get("site"))
        if site in roster.pending:
            # a re-delivered request: the daemon engine's cache_patch
            # rides EVERY retry attempt, so a failed attempt against a
            # warm worker whose live cache already drained the queue
            # re-injects the same request — the admission already
            # happened, and its pending record re-broadcasts below with
            # no second epoch bump and no second membership event
            continue
        op = str(req.get("op", "join"))
        rejoin = op == "rejoin" or site in roster.left or site in set(
            cache.get("dropped_sites", [])
        )
        epoch = roster.admit(site)
        dropped = [s for s in cache.get("dropped_sites", []) if s != site]
        if dropped != cache.get("dropped_sites", []):
            cache["dropped_sites"] = dropped
        admission = {
            **dict(cache.get("fold") or {}),
            "pretrain": False,
            "target_batches": cache.get("target_batches"),
            **dict(req.get("sync") or {}),
            RemoteWire.ROSTER_EPOCH.value: epoch,
        }
        admissions[site] = admission
        roster.pending[site] = admission
        rec.event(
            Membership.EVENT_REJOIN if rejoin else Membership.EVENT_JOIN,
            cat="membership", site=site, epoch=epoch,
            members=len(roster.members),
            **_quorum_attrs(cache, roster),
        )
        logger.warn(
            f"membership: {'re-admitted' if rejoin else 'admitted'} {site} "
            f"at roster epoch {epoch} ({len(roster.members)} members)"
        )
    roster.save(cache)
    return dict(roster.pending)


def retire_leaving(cache, input_dict):
    """Retire every site whose round output carries the
    :attr:`~..config.keys.LocalWire.LEAVING` flag — called AFTER the
    reduce consumed their final contribution, so a graceful leave costs
    nothing: the payload counts, the site retires, the epoch bumps, and
    the next round's quorum is judged against the shrunken roster.
    Returns the retired site list."""
    leavers = [
        site for site, site_vars in input_dict.items()
        if isinstance(site_vars, dict)
        and site_vars.get(LocalWire.LEAVING.value)
    ]
    if not leavers:
        return []
    roster = MembershipRoster.load(cache)
    if roster is None:
        return []
    rec = telemetry.get_active()
    for site in leavers:
        epoch = roster.retire(site)
        rec.event(
            Membership.EVENT_LEAVE, cat="membership", site=str(site),
            epoch=epoch, members=len(roster.members),
            **_quorum_attrs(cache, roster),
        )
        logger.warn(
            f"membership: {site} left gracefully at roster epoch {epoch} "
            f"({len(roster.members)} members remain)"
        )
    roster.save(cache)
    return leavers


def _quorum_attrs(cache, roster):
    """The quorum-headroom evidence membership events carry when a policy
    is configured — the live plane's ``quorum_erosion`` verdict reads it."""
    quorum = cache.get("site_quorum")
    if not quorum:
        return {}
    try:
        return {"quorum_need": max(roster.quorum_need(quorum), 1)}
    except ValueError:
        return {}
