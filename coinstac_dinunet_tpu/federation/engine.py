"""SiteVectorizedEngine — the mega-federation lifecycle driver.

Runs the full :class:`~..engine.MeshEngine` federated lifecycle (folds,
lockstep epochs, validation cadence, exact cross-site count-merge, best
checkpoints, early stop, results zip) with the gradient plane swapped for
:class:`~.vector.SiteVectorizedFederation` — B simulated sites per compiled
step, no device-count ceiling — and the resilience surface of
:class:`~..engine.InProcessEngine` restored at the per-site round boundary:

- **chaos invoke faults** (``fault_plan=``, :mod:`~..resilience.chaos`)
  fire per round + site exactly like the serial engines'; a crash/hang
  marks the site dead.  There is no per-site invocation to retry (the
  round is one jit), so a crash is immediately a dropout — transient
  faults that the serial engines recover via invoke retry kill the site
  here, which is the honest semantic of a vectorized plane.
- **site_quorum dropout contract**: without ``site_quorum`` a dead site
  fails the run loudly (all-site lockstep); with it, the dead site's
  batches degrade to fully-masked placeholders — weight 0 in the in-jit
  reduce, excluded from eval — so aggregates are survivor-weighted with
  exactly the reducer's math, and quorum is judged against the ORIGINAL
  roster with the same integral-count/fraction normalization as
  :meth:`~..nodes.remote.COINNRemote._quorum_need`.
- **telemetry**: an ``engine`` lane records per-round spans, ``site_died``
  events (doctor-attributable) and quorum decisions when any arg channel
  carries ``profile``/``telemetry``.

At ISSUE-6 scale this is the "kill 5% of 2,000 sites" story:
:func:`~..resilience.chaos.fraction_kill_plan` builds the deterministic
plan, this engine absorbs the deaths, and the stacked step never changes
shape (dead sites ride along fully masked).
"""
import time

import numpy as np

from ..config.keys import Live, Metric
from ..engine import MeshEngine
from ..nodes.remote import COINNRemote
from ..resilience.chaos import ChaosFault, ChaosSession
from ..telemetry import perf as _perf
from ..utils import logger
from .vector import SiteVectorizedFederation


class SiteVectorizedEngine(MeshEngine):
    """Full federated lifecycle over the site-vectorized gradient plane."""

    def __init__(self, workdir, n_sites, fault_plan=None, site_shards=None,
                 **kw):
        kw.pop("devices_per_site", None)  # no per-site device rank here
        super().__init__(workdir, n_sites, **kw)
        self.chaos = ChaosSession.from_spec(fault_plan)
        self.site_shards = site_shards
        self.rounds = 0
        self.site_failures = {}
        self._round_t = None  # (wall, perf) stamp of the previous hook

    # ------------------------------------------------------ federation plane
    def _build_federation(self, rc):
        sp = int(rc.get("sequence_parallel", 1) or 1)
        tp = int(rc.get("tensor_parallel", 1) or 1)
        if sp > 1 or tp > 1:
            raise ValueError(
                "SiteVectorizedEngine vectorizes the SITE axis only; "
                f"sequence_parallel={sp}/tensor_parallel={tp} need the "
                "per-rank MeshEngine"
            )
        fed = SiteVectorizedFederation(
            self._trainer, self.n_sites,
            agg_engine=str(rc.get("agg_engine", "dSGD")),
            devices=self.devices, site_shards=self.site_shards,
        )
        # the engine-lane recorder doubles as the vectorized plane's perf
        # sink (jit_cost of the one-jit round + per-step wall time)
        fed.recorder = self._recorder()
        return fed

    # --------------------------------------------------------- site dropout
    def _site_failure(self, s, exc):
        """A chaos fault killed site ``s`` this round.  Without
        ``site_quorum`` the failure propagates (all-site lockstep); with it
        the site is dead from this round on — survivor-weighted semantics,
        judged against the original roster."""
        quorum = self.cache.get("site_quorum")
        if not quorum:
            raise exc
        self.dead_sites.add(s)
        self.site_failures[s] = f"{type(exc).__name__}: {exc}"
        self._recorder().event(
            "site_died", cat="quorum", site=s, error=self.site_failures[s],
            attempts=1, retries_exhausted=False,
        )
        logger.warn(
            f"site {s} died at round {self.rounds} "
            f"({self.site_failures[s]}); excluded from the remaining rounds "
            "(site_quorum set)"
        )
        alive = [x for x in self.site_ids if x not in self.dead_sites]
        need = max(COINNRemote._quorum_need(quorum, self.n_sites), 1)
        if len(alive) < need:
            self._recorder().event(
                "quorum:fail", cat="quorum", reason="quorum unmet",
                alive=alive, need=need,
                dropped=sorted(self.dead_sites),
            )
            raise RuntimeError(
                f"quorum unmet: {len(alive)} sites alive, quorum {quorum} "
                f"of {self.n_sites} requires >= {need}; dead: "
                f"{sorted(self.dead_sites)}"
            )
        self._recorder().event(
            "quorum:continue", cat="quorum", alive=alive,
            dropped=sorted(self.dead_sites),
        )

    def _round_hook(self, site_batches):
        """The per-site round boundary of the vectorized plane: chaos
        invoke faults fire here, and dead sites' batches degrade to
        fully-masked placeholders (weight 0 in the compiled reduce) so the
        stacked step never changes shape.

        Perf flight recorder: each hook closes the PREVIOUS round — an
        ``engine:round`` span (hook-to-hook wall time, the same round
        definition ``scripts/bench_federation.py`` times) plus
        ``rounds_per_sec`` / ``sites_per_sec`` metric records and one
        device-memory sample, so the doctor's throughput trend and
        roofline cover the mega-federation path."""
        rec = self._recorder()
        now_wall, now = time.time(), time.perf_counter()
        prev, self._round_t = self._round_t, (now_wall, now)
        if prev is not None and rec.enabled:
            dt = now - prev[1]
            rec.record_span("engine:round", prev[0], dt, cat="engine",
                            round=self.rounds)
            if dt > 0:
                alive = len(self.site_ids) - len(self.dead_sites)
                rec.metric(Metric.ROUNDS_PER_SEC, 1.0 / dt,
                           round=self.rounds)
                rec.metric(Metric.SITES_PER_SEC, alive / dt,
                           round=self.rounds)
            _perf.sample_device_memory(self.cache, recorder=rec)
        self.rounds += 1
        rec.set_context(round=self.rounds)
        if rec.enabled:
            # one liveness pulse per ROUND (not per site: at 10^3 stacked
            # sites per jit, per-site events would dwarf the payload) —
            # the live board keys vectorized-plane progress on it
            rec.event(
                Live.HEARTBEAT, cat="engine",
                alive=len(self.site_ids) - len(self.dead_sites),
            )
        try:
            for s in self.site_ids:
                if s in self.dead_sites:
                    continue
                try:
                    self.chaos.invoke_fault(self.rounds, s, rec)
                except ChaosFault as exc:
                    self._site_failure(s, exc)
            if len(self.dead_sites) >= len(self.site_ids):
                raise RuntimeError(
                    f"every site died; failures: {self.site_failures}"
                )
        finally:
            # unlike the serial engines there is no per-round node flush, so
            # the engine lane flushes here — including on a quorum-unmet
            # abort, where the site_died/quorum events ARE the postmortem
            rec.flush()
        if self.dead_sites:
            for i, s in enumerate(self.site_ids):
                if s in self.dead_sites and site_batches[i] is not None:
                    site_batches[i] = [
                        {**b,
                         "_mask": np.zeros_like(np.asarray(b["_mask"]))}
                        for b in site_batches[i]
                    ]
        return site_batches
