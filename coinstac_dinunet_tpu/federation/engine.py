"""SiteVectorizedEngine — the mega-federation lifecycle driver.

Runs the full :class:`~..engine.MeshEngine` federated lifecycle (folds,
lockstep epochs, validation cadence, exact cross-site count-merge, best
checkpoints, early stop, results zip) with the gradient plane swapped for
:class:`~.vector.SiteVectorizedFederation` — B simulated sites per compiled
step, no device-count ceiling — and the resilience surface of
:class:`~..engine.InProcessEngine` restored at the per-site round boundary:

- **chaos invoke faults** (``fault_plan=``, :mod:`~..resilience.chaos`)
  fire per round + site exactly like the serial engines'; a crash/hang
  marks the site dead.  There is no per-site invocation to retry (the
  round is one jit), so a crash is immediately a dropout — transient
  faults that the serial engines recover via invoke retry kill the site
  here, which is the honest semantic of a vectorized plane.
- **site_quorum dropout contract**: without ``site_quorum`` a dead site
  fails the run loudly (all-site lockstep); with it, the dead site's
  batches degrade to fully-masked placeholders — weight 0 in the in-jit
  reduce, excluded from eval — so aggregates are survivor-weighted with
  exactly the reducer's math, and quorum is judged against the ORIGINAL
  roster with the same integral-count/fraction normalization as
  :meth:`~..nodes.remote.COINNRemote._quorum_need`.
- **telemetry**: an ``engine`` lane records per-round spans, ``site_died``
  events (doctor-attributable) and quorum decisions when any arg channel
  carries ``profile``/``telemetry``.

At ISSUE-6 scale this is the "kill 5% of 2,000 sites" story:
:func:`~..resilience.chaos.fraction_kill_plan` builds the deterministic
plan, this engine absorbs the deaths, and the stacked step never changes
shape (dead sites ride along fully masked).
"""
import time

import numpy as np

from ..config.keys import Live, Membership, Metric
from ..engine import MeshEngine
from ..nodes.remote import COINNRemote
from ..resilience.chaos import ChaosFault, ChaosSession
from ..telemetry import perf as _perf
from ..utils import logger
from .vector import SiteVectorizedFederation


class SiteVectorizedEngine(MeshEngine):
    """Full federated lifecycle over the site-vectorized gradient plane.

    Elastic membership (ISSUE 15) rides the roster MASK, never the shape:
    the stacked site axis is allocated once at a **capacity high-water
    mark** (``n_sites`` founding members + ``spare_capacity`` empty
    slots, derived from the churn plan when one is loaded), and every
    membership change — graceful leave, mid-run join into a spare slot,
    rejoin after a chaos death — only flips that slot between live
    batches and the fully-masked placeholder stream (weight 0 in the
    in-jit reduce).  The one-jit round therefore NEVER recompiles on
    churn.  Data granularity follows the vectorized plane's lockstep
    epochs: a join/rejoin re-arms the slot's loader at the next epoch
    boundary (roster and quorum effect is immediate), a leave masks the
    slot from the very next round.
    """

    def __init__(self, workdir, n_sites, fault_plan=None, site_shards=None,
                 spare_capacity=None, **kw):
        kw.pop("devices_per_site", None)  # no per-site device rank here
        chaos = ChaosSession.from_spec(fault_plan)
        if spare_capacity is None:
            # every join in the plan targets a slot past the founding
            # roster — allocate exactly those spares so churn plans from
            # resilience.chaos.churn_plan work unconfigured
            spare_capacity = sum(
                1 for f in getattr(chaos, "faults", ())
                if f.kind == "join"
            )
        self.founding_sites = int(n_sites)
        self.capacity = int(n_sites) + int(spare_capacity)
        super().__init__(workdir, self.capacity, **kw)
        self.chaos = chaos
        self.site_shards = site_shards
        self.rounds = 0
        self.site_failures = {}
        # elastic-membership roster (ISSUE 15): spare slots are allocated
        # but not yet admitted; left slots were members and retired
        # gracefully.  A dead site REMAINS a roster member (PR-9
        # semantics) until a rejoin re-admits it or the run ends.
        self.spare_sites = set(self.site_ids[self.founding_sites:])
        self.left_sites = set()
        self.roster_epoch = 1
        self._membership_counts = {"join": 0, "leave": 0, "rejoin": 0}
        self._round_t = None  # (wall, perf) stamp of the previous hook

    # ----------------------------------------------- elastic membership (15)
    def _member_ids(self):
        """The CURRENT roster: founding + admitted spares − retired."""
        return [
            s for s in self.site_ids
            if s not in self.spare_sites and s not in self.left_sites
        ]

    def _site_loads(self, s):
        """Only live roster members get live loaders: a spare (not yet
        admitted) or retired slot rides fully masked even when its data
        directory is populated — it must not contribute to any reduce."""
        return (s not in self.dead_sites and s not in self.spare_sites
                and s not in self.left_sites)

    def add_site(self, site_id=None):
        """Mid-run JOIN/REJOIN on the vectorized plane: activate a spare
        slot (join), or re-admit a retired or dead slot (rejoin) — the
        ``dead_sites`` exclusion is REVERSIBLE through this path (the
        grow-only mask was the PR-15 satellite bug: a healed site stayed
        excluded from the reduce mask forever).  The roster/quorum effect
        is immediate; the slot's loader re-arms at the next epoch
        boundary (lockstep-epoch data granularity).  Returns the slot id.
        """
        rec = self._recorder()
        if site_id is None:
            site_id = next(iter(sorted(self.spare_sites)), None)
            if site_id is None:
                raise ValueError(
                    "no spare capacity left: size the engine's "
                    "spare_capacity to the expected join volume (the "
                    "stacked site axis cannot grow without recompiling)"
                )
        site_id = str(site_id)
        if site_id not in self.site_states:
            raise ValueError(
                f"{site_id} is outside the allocated capacity "
                f"({self.capacity} slots)"
            )
        rejoin = site_id in self.left_sites or site_id in self.dead_sites
        if not rejoin and site_id not in self.spare_sites:
            raise ValueError(f"{site_id} is already an active member")
        self.spare_sites.discard(site_id)
        self.left_sites.discard(site_id)
        self.dead_sites.discard(site_id)
        self.site_failures.pop(site_id, None)
        self.roster_epoch += 1
        kind = "rejoin" if rejoin else "join"
        self._membership_counts[kind] += 1
        rec.event(
            Membership.EVENT_REJOIN if rejoin else Membership.EVENT_JOIN,
            cat="membership", site=site_id, epoch=self.roster_epoch,
            members=len(self._member_ids()),
        )
        logger.warn(
            f"membership: {site_id} {'re-joined' if rejoin else 'joined'} "
            f"the vectorized federation at roster epoch {self.roster_epoch} "
            f"({len(self._member_ids())} members; data re-arms at the next "
            "epoch boundary)"
        )
        return site_id

    def remove_site(self, site_id, graceful=True):
        """Mid-run LEAVE: retire a member — its slot is masked from the
        next round on, the roster epoch bumps, and quorum is judged
        against the shrunken roster.  Graceful (default) never fires
        ``site_died``; ``graceful=False`` routes through the death path
        (a chaos-equivalent operator kill)."""
        site_id = str(site_id)
        if site_id not in self._member_ids() or site_id in self.dead_sites:
            raise ValueError(f"{site_id} is not an alive member")
        if not graceful:
            self._site_failure(
                site_id, RuntimeError("removed by operator")
            )
            return
        self.left_sites.add(site_id)
        self.roster_epoch += 1
        self._membership_counts["leave"] += 1
        self._recorder().event(
            Membership.EVENT_LEAVE, cat="membership", site=site_id,
            epoch=self.roster_epoch, members=len(self._member_ids()),
        )
        logger.warn(
            f"membership: {site_id} left the vectorized federation "
            f"gracefully at roster epoch {self.roster_epoch} "
            f"({len(self._member_ids())} members remain)"
        )

    def _membership_round(self, rec):
        """Apply the chaos churn plan's roster transitions pinned to this
        round (:func:`~..resilience.chaos.churn_plan`)."""
        for kind, s in self.chaos.membership_ops(self.rounds, rec):
            try:
                if kind == "leave":
                    self.remove_site(s, graceful=True)
                else:  # join / rejoin
                    self.add_site(s)
            except ValueError as exc:
                logger.warn(f"churn plan op {kind}@{s} skipped: {exc}")

    # ------------------------------------------------------ federation plane
    def _build_federation(self, rc):
        sp = int(rc.get("sequence_parallel", 1) or 1)
        tp = int(rc.get("tensor_parallel", 1) or 1)
        if sp > 1 or tp > 1:
            raise ValueError(
                "SiteVectorizedEngine vectorizes the SITE axis only; "
                f"sequence_parallel={sp}/tensor_parallel={tp} need the "
                "per-rank MeshEngine"
            )
        fed = SiteVectorizedFederation(
            self._trainer, self.n_sites,
            agg_engine=str(rc.get("agg_engine", "dSGD")),
            devices=self.devices, site_shards=self.site_shards,
        )
        # the engine-lane recorder doubles as the vectorized plane's perf
        # sink (jit_cost of the one-jit round + per-step wall time)
        fed.recorder = self._recorder()
        return fed

    # --------------------------------------------------------- site dropout
    def _site_failure(self, s, exc):
        """A chaos fault killed site ``s`` this round.  Without
        ``site_quorum`` the failure propagates (all-site lockstep); with it
        the site is dead from this round on — survivor-weighted semantics,
        judged against the CURRENT roster (ISSUE 15: a gracefully retired
        site neither counts as alive nor inflates the need; a mid-run
        joiner extends both)."""
        quorum = self.cache.get("site_quorum")
        if not quorum:
            raise exc
        self.dead_sites.add(s)
        self.site_failures[s] = f"{type(exc).__name__}: {exc}"
        self._recorder().event(
            "site_died", cat="quorum", site=s, error=self.site_failures[s],
            attempts=1, retries_exhausted=False,
        )
        logger.warn(
            f"site {s} died at round {self.rounds} "
            f"({self.site_failures[s]}); excluded from the remaining rounds "
            "(site_quorum set)"
        )
        members = self._member_ids()
        alive = [x for x in members if x not in self.dead_sites]
        need = max(COINNRemote._quorum_need(quorum, len(members)), 1)
        if len(alive) < need:
            self._recorder().event(
                "quorum:fail", cat="quorum", reason="quorum unmet",
                alive=alive, need=need,
                dropped=sorted(self.dead_sites),
            )
            raise RuntimeError(
                f"quorum unmet: {len(alive)} sites alive, quorum {quorum} "
                f"of {len(members)} roster members requires >= {need}; "
                f"dead: {sorted(self.dead_sites)}"
            )
        self._recorder().event(
            "quorum:continue", cat="quorum", alive=alive,
            dropped=sorted(self.dead_sites),
        )

    def _round_hook(self, site_batches):
        """The per-site round boundary of the vectorized plane: chaos
        invoke faults fire here, and dead sites' batches degrade to
        fully-masked placeholders (weight 0 in the compiled reduce) so the
        stacked step never changes shape.

        Perf flight recorder: each hook closes the PREVIOUS round — an
        ``engine:round`` span (hook-to-hook wall time, the same round
        definition ``scripts/bench_federation.py`` times) plus
        ``rounds_per_sec`` / ``sites_per_sec`` metric records and one
        device-memory sample, so the doctor's throughput trend and
        roofline cover the mega-federation path."""
        rec = self._recorder()
        now_wall, now = time.time(), time.perf_counter()
        prev, self._round_t = self._round_t, (now_wall, now)
        if prev is not None and rec.enabled:
            dt = now - prev[1]
            rec.record_span("engine:round", prev[0], dt, cat="engine",
                            round=self.rounds)
            if dt > 0:
                alive = len([
                    s for s in self._member_ids()
                    if s not in self.dead_sites
                ])
                rec.metric(Metric.ROUNDS_PER_SEC, 1.0 / dt,
                           round=self.rounds)
                rec.metric(Metric.SITES_PER_SEC, alive / dt,
                           round=self.rounds)
            _perf.sample_device_memory(self.cache, recorder=rec)
        self.rounds += 1
        rec.set_context(round=self.rounds)
        # elastic membership first: this round's churn plan transitions
        # re-scope the roster BEFORE faults fire and masks apply
        self._membership_round(rec)
        members = self._member_ids()
        if rec.enabled:
            # one liveness pulse per ROUND (not per site: at 10^3 stacked
            # sites per jit, per-site events would dwarf the payload) —
            # the live board keys vectorized-plane progress on it
            rec.event(
                Live.HEARTBEAT, cat="engine",
                alive=len([s for s in members
                           if s not in self.dead_sites]),
            )
        try:
            for s in members:
                if s in self.dead_sites:
                    continue
                try:
                    self.chaos.invoke_fault(self.rounds, s, rec)
                except ChaosFault as exc:
                    self._site_failure(s, exc)
            if all(s in self.dead_sites for s in self._member_ids()):
                raise RuntimeError(
                    f"every roster member died; failures: "
                    f"{self.site_failures}"
                )
        finally:
            # unlike the serial engines there is no per-round node flush, so
            # the engine lane flushes here — including on a quorum-unmet
            # abort, where the site_died/quorum events ARE the postmortem
            rec.flush()
        # the roster mask: dead, retired and not-yet-admitted slots all
        # degrade to fully-masked placeholders — weight 0 in the in-jit
        # reduce, the stacked shape untouched (no recompile on churn)
        masked = self.dead_sites | self.left_sites | self.spare_sites
        if masked:
            for i, s in enumerate(self.site_ids):
                if s in masked and site_batches[i] is not None:
                    site_batches[i] = [
                        {**b,
                         "_mask": np.zeros_like(np.asarray(b["_mask"]))}
                        for b in site_batches[i]
                    ]
        return site_batches
