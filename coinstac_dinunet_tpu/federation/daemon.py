"""Persistent engine daemon: warm workers serve federation rounds like traffic.

The paper's process model re-pays interpreter start, imports and jit
compilation on EVERY node invocation (``SubprocessEngine`` spawns
``python <script>`` per site per round; BENCH_r03–r05 measured backend init
alone above 900 s) — orders of magnitude behind the in-process engines on
heavy traffic.  :class:`DaemonEngine` keeps the fresh-process deployment's
isolation (one OS process per node, the ``{cache, input, state}`` →
``{output, cache}`` JSON contract preserved exactly at the boundary, the
same ``examples/*/local.py`` / ``remote.py`` scripts UNMODIFIED) but starts
each node's process **once**: a long-lived worker per site plus one for the
aggregator, each holding the warm backend, device buffers, compiled
executables and the live (non-JSON) node cache across rounds — the
mesh-once/jit-many shape of Podracer-style long-lived actors (PAPERS.md
arXiv:2104.06272).

Wire format — framed JSON over the worker's stdin/stdout, length-prefixed
so a payload may contain anything (including newlines)::

    COINND1 <nbytes>\\n<nbytes of JSON>\\n

Requests: ``{"op": "invoke", "round": r, "payload": {cache,input,state}}``
(plus ``ping``/``shutdown``); responses: ``{"ok": true, "result":
{"output": ..., "cache": ...}}`` or ``{"ok": false, "error", "traceback"}``.
The worker's fd 1 is reserved for frames at startup (stray ``print`` from
node code is rerouted to stderr, which lands in the per-worker log under
``<workdir>/daemon_logs/``).

Steady-state frames skip the same-host copy tax (ISSUE 14): the worker's
ready frame advertises ``delta: true``, after which (a) the engine OMITS
the inbound JSON cache once it has confirmed the worker warm at the
current generation (the worker owns the live cache and ignored the copy
anyway), and (b) warm responses replace the full ``"cache"`` re-dump with
``"cache_delta": {"set": {...}, "del": [...]}`` — the dirty keys since
the last shipped cache — which the engine folds into its mirror so every
caller still sees the full JSON cache.  A restarted worker always drops
back to full-cache frames (exactly what it resumes from).  Frames are not
key-sorted (determinism belongs in tests, not the steady-state pipe), and
every invocation lands a ``daemon:frame`` event with its tx/rx byte
counts so the delta win is measurable on the live plane.

Supervision (the part that makes a long-lived process deployable): a
crashed or wedged worker is killed and **restarted** — not declared a dead
site — under :meth:`~..resilience.retry.RetryPolicy.for_worker`
(``worker_restart_*`` cache keys, default ON with 3 attempts), with typed
``worker:start``/``worker:restart`` events (:class:`~..config.keys.Daemon`)
on the engine telemetry lane and the usual ``engine:heartbeat`` per
completed invocation, so ``telemetry watch``, ``/metrics`` and ``/healthz``
monitor the daemon natively.  The restart path re-invokes the node with the
engine's round-tripped JSON cache; the live train state restores from the
per-round on-disk record (``cache['persist_round_state']`` — required for
mid-run restart survival, exactly like the fresh-process engine), and the
fresh process skips recompilation because the daemon enables the persistent
XLA compilation cache (``utils.maybe_enable_compilation_cache``) by
default (``<workdir>/xla_cache``; pass ``compilation_cache_dir=False`` to
opt out).  The ``worker_kill`` chaos fault
(:mod:`~..resilience.chaos`) SIGKILLs a worker deterministically so CI can
drill the whole restart path; the tier-4 model checker explores the
matching ``worker_crash``/``worker_restart`` actions
(:mod:`~..analysis.model_check`).

Run ``python -m coinstac_dinunet_tpu.federation.daemon <script>`` to start
a worker by hand (the engine does this for you).
"""
import json
import os
import select
import subprocess
import sys
import threading
import time
import traceback

from .. import utils
from ..config.keys import Daemon, Membership
from ..engine import SubprocessEngine
from ..resilience.retry import RetryPolicy

#: frame magic — version-stamped so a protocol change fails loudly
MAGIC = b"COINND1"
#: worker env var naming the persistent XLA compilation cache directory
#: (the worker feeds it to ``utils.maybe_enable_compilation_cache`` before
#: the node script imports, so even a restarted worker skips recompiles)
COMPILATION_CACHE_ENV = "COINN_DAEMON_COMPILATION_CACHE"
_READ_CHUNK = 1 << 16


class WorkerUnavailable(RuntimeError):
    """The worker process (not the node code) failed: crashed, wedged, or
    unreachable.  The daemon's supervision policy retries these by
    RESTARTING the worker; node-level errors raise plain RuntimeError and
    flow to the (default-off) invoke retry + quorum machinery instead."""


class WorkerCrashed(WorkerUnavailable):
    """The worker died (EOF/broken pipe/bad handshake); message carries the
    stderr-log tail."""


class WorkerTimeout(WorkerUnavailable):
    """The worker produced no response frame within the engine timeout."""


# ------------------------------------------------------------------ framing
def write_frame(stream, obj):
    """One length-prefixed JSON frame; flushes (the peer blocks on it).
    Returns the frame size in bytes (the hot-path wire-telemetry counter).

    No ``sort_keys``: key order is not part of the frame contract (the
    peer decodes to a dict), and sorting every per-invoke frame taxes the
    steady-state pipe for a determinism only tests want — a test that
    needs canonical bytes sorts its own ``json.dumps``."""
    data = json.dumps(obj).encode("utf-8")
    stream.write(MAGIC + b" %d\n" % len(data))
    stream.write(data)
    stream.write(b"\n")
    stream.flush()
    return len(MAGIC) + len(data) + len(b" %d\n" % len(data)) + 1


def read_frame(stream):
    """Blocking frame read (worker side).  Returns the decoded object, or
    None on EOF at a frame boundary; raises ValueError on a malformed
    header/body (protocol desync — the worker dies loudly and the
    supervisor replaces it)."""
    header = stream.readline()
    if not header:
        return None
    parts = header.strip().split()
    if len(parts) != 2 or parts[0] != MAGIC:
        raise ValueError(f"bad frame header {header[:80]!r}")
    n = int(parts[1])
    data = b""
    while len(data) < n:
        chunk = stream.read(n - len(data))
        if not chunk:
            return None  # EOF mid-frame: peer died; nothing to salvage
        data += chunk
    stream.read(1)  # the trailing newline
    return json.loads(data.decode("utf-8"))


class _FrameReader:
    """Deadline-bounded frame reads off a worker's stdout pipe (engine
    side): ``select`` + ``os.read`` into a buffer, frames parsed out as
    they complete — a wedged worker raises :class:`WorkerTimeout` instead
    of blocking the engine forever."""

    def __init__(self, stream):
        self._fd = stream.fileno()
        self._buf = b""
        #: cumulative frame bytes consumed — the engine samples it around
        #: each request for the per-invoke wire telemetry
        self.bytes_read = 0

    def _parse(self):
        """(frame, consumed) — frame is None while incomplete."""
        nl = self._buf.find(b"\n")
        if nl < 0:
            return None
        parts = self._buf[:nl].split()
        if len(parts) != 2 or parts[0] != MAGIC:
            raise WorkerCrashed(
                f"worker protocol desync: bad frame header "
                f"{self._buf[:80]!r} (node code wrote to the frame fd?)"
            )
        n = int(parts[1])
        end = nl + 1 + n + 1
        if len(self._buf) < end:
            return None
        data = self._buf[nl + 1:nl + 1 + n]
        self._buf = self._buf[end:]
        self.bytes_read += end
        return json.loads(data.decode("utf-8"))

    def read_frame(self, timeout):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            frame = self._parse()
            if frame is not None:
                return frame
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise WorkerTimeout(
                        f"no response frame within {timeout}s"
                    )
            ready, _, _ = select.select([self._fd], [], [], remaining)
            if not ready:
                raise WorkerTimeout(f"no response frame within {timeout}s")
            chunk = os.read(self._fd, _READ_CHUNK)
            if not chunk:
                raise WorkerCrashed("worker closed its frame pipe (died)")
            self._buf += chunk


# -------------------------------------------------------------- worker loop
def _load_compute(script):
    """Import the node script ONCE (warm imports + backend for every later
    round) with ``__name__`` != ``"__main__"`` so its one-shot
    read-stdin-once block does not run — the scripts stay byte-for-byte
    the ones the fresh-process engine executes."""
    import importlib.util

    script = os.path.abspath(script)
    sys.path.insert(0, os.path.dirname(script))
    spec = importlib.util.spec_from_file_location(
        f"_coinn_daemon_node_{os.getpid()}", script
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    compute = getattr(mod, "compute", None)
    if not callable(compute):
        raise TypeError(
            f"{script} defines no compute(payload) function — the daemon "
            "worker drives the same entry point the one-shot __main__ "
            "block wraps (see examples/*/local.py)"
        )
    return compute


def worker_main(argv=None):
    """``python -m coinstac_dinunet_tpu.federation.daemon <script>``: the
    long-lived worker loop.  fd 1 is reserved for frames before anything
    else runs; node prints land on stderr (the per-worker log)."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) != 1:
        print("usage: python -m coinstac_dinunet_tpu.federation.daemon "
              "<node_script.py>", file=sys.stderr)
        return 2
    # reserve the frame channel, then point fd 1 (and the sys.stdout
    # object) at stderr so a stray print can never corrupt a frame
    out = os.fdopen(os.dup(sys.__stdout__.fileno()), "wb")
    os.dup2(sys.__stderr__.fileno(), sys.__stdout__.fileno())
    sys.stdout = sys.stderr

    ccdir = os.environ.get(COMPILATION_CACHE_ENV)
    if ccdir:
        # before the script import triggers any jit: a restarted worker's
        # first compile becomes a disk-cache hit
        utils.maybe_enable_compilation_cache({"compilation_cache_dir": ccdir})
    try:
        compute = _load_compute(argv[0])
    except BaseException:  # noqa: BLE001 — ship the import failure upstream
        traceback.print_exc()
        write_frame(out, {"ok": False, "op": "ready",
                          "error": traceback.format_exc()[-2000:]})
        return 2
    # ``delta: True`` advertises the dirty-key cache protocol: a warm
    # engine may omit the inbound JSON cache (this worker owns the live
    # one), and warm responses carry a ``cache_delta`` of changed/removed
    # keys instead of re-serializing the full JSON cache every invocation
    write_frame(out, {"ok": True, "op": "ready", "pid": os.getpid(),
                      "delta": True})

    stdin = sys.stdin.buffer
    # the warm heart of the daemon: the live cache dict (holding the
    # non-JSON train state, compiled steps, data handles) survives between
    # rounds exactly like InProcessEngine's per-site cache dict — the
    # engine's JSON copy is only the durable fallback a RESTARTED worker
    # rebuilds from (via persist_round_state)
    live_cache = None
    # the JSON-clean cache this worker last shipped (and the engine
    # acknowledged by not restarting us): the base the next response's
    # dirty-key delta is computed against.  Only updates when a cache
    # actually ships — a node-error response carries none, so the
    # engine's copy and this base stay in lockstep.
    last_clean_cache = None
    while True:
        msg = read_frame(stdin)  # ValueError on desync: die; be restarted
        if msg is None or msg.get("op") == "shutdown":
            return 0
        if msg.get("op") == "ping":
            write_frame(out, {"ok": True, "op": "pong", "pid": os.getpid()})
            continue
        if msg.get("op") != "invoke":
            write_frame(out, {"ok": False, "pid": os.getpid(),
                              "error": f"unknown op {msg.get('op')!r}"})
            continue
        # the request's round stamp, echoed verbatim on every response
        # (success or node error) so the engine can refuse a frame that
        # answers a different round than the one it just asked — the
        # frame-lane twin of the wire_round echo in the node handshake
        rnd = msg.get("round")
        payload = dict(msg.get("payload") or {})
        # engine-authored cache writes (elastic-membership admission
        # requests, ISSUE 15) ride as an explicit patch: a warm worker
        # owns the live cache and discards the inbound JSON copy below,
        # so anything the ENGINE wrote into its copy between rounds would
        # otherwise silently never reach the node
        patch = payload.pop("cache_patch", None)
        payload.setdefault("cache", {})
        warm = live_cache is not None
        if warm:
            payload["cache"] = live_cache
        if patch:
            payload["cache"].update(patch)
        try:
            result = compute(payload)
            live_cache = payload["cache"]
            resp = {
                "ok": True, "pid": os.getpid(), "warm": warm,
                "round": rnd,
                "result": utils.clean_recursive(result),
            }
            clean = resp["result"]
            cc = clean.get("cache") if isinstance(clean, dict) else None
            if isinstance(cc, dict):
                if isinstance(last_clean_cache, dict):
                    # dirty-key delta vs the last shipped cache: the
                    # steady state re-serializes only what changed (the
                    # logs that grew, the cursor) instead of the whole
                    # cache — the same-host copy-tax teardown of ISSUE 14
                    changed = {
                        k: v for k, v in cc.items()
                        if k not in last_clean_cache
                        or last_clean_cache[k] != v
                    }
                    removed = [k for k in last_clean_cache if k not in cc]
                    clean = dict(clean)
                    clean.pop("cache", None)
                    clean["cache_delta"] = {"set": changed, "del": removed}
                    resp["result"] = clean
                last_clean_cache = cc
            write_frame(out, resp)
        except BaseException as exc:  # noqa: BLE001 — node error → response
            traceback.print_exc()
            # keep the (possibly half-mutated) cache for a retry — the
            # in-process engine's shared-dict semantics; a worker RESTART
            # is the clean-slate path
            live_cache = payload["cache"]
            write_frame(out, {
                "ok": False, "pid": os.getpid(), "round": rnd,
                "error": f"{type(exc).__name__}: {exc}"[:500],
                "traceback": traceback.format_exc()[-4000:],
            })


# ------------------------------------------------------------ worker handle
class _Worker:
    """One live worker process + its frame channel and stderr log."""

    def __init__(self, target, script, env, log_path, start_timeout):
        self.target = str(target)
        self.script = str(script)
        self.log_path = str(log_path)
        os.makedirs(os.path.dirname(self.log_path), exist_ok=True)
        self._log_f = open(self.log_path, "ab")
        t0 = time.monotonic()
        self.proc = subprocess.Popen(
            [sys.executable, "-m",
             "coinstac_dinunet_tpu.federation.daemon", self.script],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=self._log_f, env=env, close_fds=True,
        )
        self._reader = _FrameReader(self.proc.stdout)
        try:
            ready = self._read(start_timeout)
        except WorkerUnavailable as exc:
            self.kill()
            raise WorkerCrashed(
                f"worker for {self.target} failed to start: {exc}"
            ) from exc
        if not (ready.get("ok") and ready.get("op") == "ready"):
            err = str(ready.get("error", ready))[-2000:]
            self.kill()
            raise WorkerCrashed(
                f"worker for {self.target} failed its ready handshake: {err}"
            )
        self.pid = int(ready.get("pid") or self.proc.pid)
        self.warm_s = time.monotonic() - t0
        #: the worker speaks the dirty-key cache-delta protocol (always
        #: true for in-tree workers; the flag keeps a handshake-level
        #: opt-out for out-of-tree worker loops)
        self.delta = bool(ready.get("delta"))
        #: frame bytes of the last request/response pair (wire telemetry)
        self.last_tx = 0
        self.last_rx = 0

    def alive(self):
        return self.proc.poll() is None

    def _read(self, timeout):
        try:
            return self._reader.read_frame(timeout)
        except WorkerTimeout:
            raise
        # OSError/ValueError: the pipe fd was closed under us (a chaos
        # kill between the send and the read) — same observable as a crash
        except (WorkerCrashed, OSError, ValueError) as exc:
            rc = self.proc.poll()
            raise WorkerCrashed(
                f"worker {self.target} (pid {self.proc.pid}) died "
                f"(rc={rc}): {exc}\n--- stderr tail ---\n"
                f"{self.stderr_tail()}"
            ) from exc

    def request(self, obj, timeout):
        try:
            self.last_tx = write_frame(self.proc.stdin, obj)
        except (BrokenPipeError, OSError, ValueError) as exc:
            raise WorkerCrashed(
                f"worker {self.target} (pid {self.proc.pid}) pipe closed: "
                f"{exc}\n--- stderr tail ---\n{self.stderr_tail()}"
            ) from exc
        before = self._reader.bytes_read
        frame = self._read(timeout)
        self.last_rx = self._reader.bytes_read - before
        return frame

    def stderr_tail(self, nbytes=4000):
        try:
            with open(self.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                f.seek(max(f.tell() - int(nbytes), 0))
                return f.read().decode("utf-8", "replace")
        except OSError:
            return "<no stderr log>"

    def shutdown(self, grace=3.0):
        """Orderly stop: shutdown frame, short wait, then the hammer."""
        if self.alive():
            try:
                write_frame(self.proc.stdin, {"op": "shutdown"})
                self.proc.wait(timeout=grace)
            except (OSError, ValueError, subprocess.TimeoutExpired):
                pass
        self.kill()

    def kill(self):
        if self.proc.poll() is None:
            self.proc.kill()
        try:
            self.proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass
        for stream in (self.proc.stdin, self.proc.stdout):
            try:
                stream.close()
            except OSError:
                pass
        try:
            self._log_f.close()
        except OSError:
            pass


# ------------------------------------------------------------------- engine
class DaemonEngine(SubprocessEngine):
    """Fresh-process deployment at in-process speed: one long-lived warm
    worker per site (plus the aggregator), supervised restarts instead of
    dead sites, the node scripts and the ``{cache, input, state}`` →
    ``{output, cache}`` contract untouched.

    Inherits everything from :class:`~..engine.SubprocessEngine` except
    ``_invoke``: instead of spawning ``python <script>`` per invocation,
    requests go to the target's persistent worker over the framed pipe.
    The worker keeps the LIVE node cache (train state, compiled steps) in
    memory between rounds, so steady-state rounds cost what the in-process
    engine's do; the engine still round-trips the JSON cache each round,
    which is exactly what a restarted worker resumes from.

    ``compilation_cache_dir`` (default: ``<workdir>/xla_cache``; False
    disables) is exported to every worker so a restart skips
    recompilation.  Call :meth:`close` (or use the engine as a context
    manager) to shut the workers down.
    """

    def __init__(self, workdir, n_sites, local_script, remote_script,
                 first_input=None, env=None, timeout=600,
                 start_timeout=None, compilation_cache_dir=None, **kw):
        super().__init__(
            workdir, n_sites, local_script, remote_script,
            first_input=first_input, env=env, timeout=timeout, **kw,
        )
        # worker START (interpreter + imports + backend init) is a
        # different animal from a steady-state invocation: an operator
        # tuning `timeout` down for fast rounds must not make every
        # restart fail its ready handshake
        self.start_timeout = (
            float(start_timeout) if start_timeout is not None
            else max(float(timeout), 120.0)
        )
        if compilation_cache_dir is None:
            compilation_cache_dir = os.path.join(self.workdir, "xla_cache")
        self.compilation_cache_dir = compilation_cache_dir or None
        self._workers = {}
        self._worker_gen = {}
        self._worker_last_error = {}
        # dirty-key cache-delta protocol state, per target (each target is
        # driven by exactly one thread at a time — the async pool pins one
        # pending invocation per site; the aggregator rides the reducer
        # worker): the worker generation whose live cache the engine has
        # confirmed warm (matching gen => the inbound JSON cache may be
        # omitted), and the engine-side mirror of the worker's last
        # shipped clean cache that response deltas are applied to
        self._warm_gen = {}
        self._delta_base = {}
        # async-mode pool threads may still be driving a straggler's worker
        # when close() runs: the flag stops the supervisor from respawning
        # a worker for a request that is being torn down
        self._closing = False
        # worker bring-up/teardown is engine-side state the async pool
        # threads share — one lock per engine keeps a concurrent restart
        # from racing a neighbor's spawn bookkeeping
        self._worker_lock = threading.RLock()
        # joiners whose fresh worker add_site pre-warmed in the background
        # (activation must not kill it: it IS the new incarnation)
        self._prewarmed = set()
        # the daemon's capacity high-water mark (the vectorized plane's
        # spare-slot twin, ISSUE 15): a chaos churn plan names its JOIN
        # targets at build time, so their workers spawn warm NOW on
        # background threads — a mid-run admission then costs one
        # full-cache frame instead of a synchronous interpreter +
        # imports + backend cold start
        self._spare_workers = set()
        for f in getattr(self.chaos, "faults", ()):
            if f.kind == "join" and f.site and f.site not in self.site_ids:
                sid = str(f.site)
                self._spare_workers.add(sid)
                threading.Thread(
                    target=self._prewarm_worker, args=(sid,), daemon=True,
                    name=f"prewarm-{sid}",
                ).start()

    # ---------------------------------------------------------- supervision
    def _worker_env(self):
        env = dict(self.env if self.env is not None else os.environ)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ))
        env["PYTHONPATH"] = (
            pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        ).rstrip(os.pathsep)
        if self.compilation_cache_dir:
            env.setdefault(COMPILATION_CACHE_ENV,
                           str(self.compilation_cache_dir))
        return env

    def _ensure_worker(self, target, script, rec):
        """The live worker for ``target``, (re)spawning as needed — the
        single place a worker comes up, so ``worker:start`` vs
        ``worker:restart`` is decided by one generation counter.  Serialized
        per engine: async-mode pool threads may restart their own targets
        concurrently, and the spawn bookkeeping must never interleave.  A
        closing engine refuses to respawn (non-retryable, so a torn-down
        straggler request fails fast instead of resurrecting its worker)."""
        with self._worker_lock:
            if self._closing:
                raise RuntimeError(
                    f"engine is closing; refusing to (re)spawn a worker "
                    f"for {target}"
                )
            w = self._workers.get(target)
            if w is not None and w.alive():
                return w
            gen = self._worker_gen.get(target, 0)
            if w is not None:
                w.kill()  # reap the corpse; its log stays on disk
                self._workers.pop(target, None)
            w = _Worker(
                target, script, env=self._worker_env(),
                log_path=os.path.join(self.workdir, "daemon_logs",
                                      f"{target}.log"),
                start_timeout=self.start_timeout,
            )
            self._workers[target] = w
            self._worker_gen[target] = gen + 1
            last_err = self._worker_last_error.pop(target, None)
            # ``site=`` so the live ops plane attributes the churn per site
            # (the aggregator's worker rides as site="remote", excluded from
            # the per-site table exactly like its heartbeat)
            rec.event(
                Daemon.EVENT_RESTART if gen else Daemon.EVENT_START,
                cat="daemon", target=str(target), site=str(target), pid=w.pid,
                generation=gen + 1, warm_s=round(w.warm_s, 3),
                **({"error": last_err} if last_err else {}),
            )
            return w

    def _restart_policy(self, target):
        return RetryPolicy.for_worker(self._target_config(target))

    # ----------------------------------------------------------- invocation
    def _invoke(self, script, payload, target=None, rec=None, rnd=None):
        rec = rec if rec is not None else self._recorder()
        target = str(target)
        # async-mode pool threads may outlive the round they were submitted
        # in — the caller pins the round so chaos worker faults stay
        # deterministic under any completion order
        rnd = int(rnd) if rnd is not None else self.rounds + 1
        payload = utils.clean_recursive(payload)
        # engine-authored cache writes must survive the warm worker
        # replacing the inbound JSON cache with its live one: the elastic
        # membership admission queue (ISSUE 15) is written by the ENGINE
        # into its cache copy between rounds, so it rides the frame as an
        # explicit ``cache_patch`` the worker applies on top of whichever
        # cache it computes with
        patch = {
            k: (payload.get("cache") or {}).get(k)
            for k in (Membership.REQUESTS,)
            if (payload.get("cache") or {}).get(k)
        }

        def attempt():
            worker = self._ensure_worker(target, script, rec)
            fault = self.chaos.worker_fault(rnd, target, rec)
            if fault is not None:
                # the supervision drill: SIGKILL the live worker right as
                # the round reaches it — the request below finds a corpse
                worker.kill()
            # hot-path copy-tax cut (ISSUE 14): a worker the engine has
            # confirmed warm at this generation owns the live cache and
            # ignores the inbound JSON copy anyway — omit it from the
            # frame.  A restart (generation bump) always goes back to the
            # full cache, which is exactly what the fresh worker resumes
            # from.
            req = payload
            if (worker.delta and self._warm_gen.get(target)
                    == self._worker_gen.get(target)):
                req = {k: v for k, v in payload.items() if k != "cache"}
            if patch:
                req = {**req, "cache_patch": patch}
            try:
                res = worker.request(
                    {"op": "invoke", "round": rnd, "payload": req},
                    timeout=self.timeout,
                )
                echoed = res.get("round")
                if echoed is not None and echoed != rnd:
                    # a response answering some OTHER round: the frame
                    # lane is desynced (leftover/redelivered frame) —
                    # kill for a clean restart instead of handing the
                    # round a stale result.  None is tolerated as the
                    # handshake-level opt-out for out-of-tree workers
                    # that don't echo (the same latitude as ``delta``).
                    worker.kill()
                    raise WorkerCrashed(
                        f"worker {target} (pid {worker.pid}) answered "
                        f"round {echoed!r} to a round {rnd!r} request — "
                        "frame-lane desync"
                    )
                return res, worker
            except WorkerTimeout as exc:
                # same typed attribution as the fresh-process engine's
                # TimeoutExpired mapping; the wedged process is killed so
                # the NEXT attempt restarts rather than re-wedges
                rec.event(
                    "invoke:timeout", cat="invoke", target=target,
                    timeout_s=float(self.timeout),
                    stderr=worker.stderr_tail(1000),
                )
                worker.kill()
                raise WorkerTimeout(
                    f"worker {target} (pid {worker.pid}) gave no response "
                    f"within {self.timeout}s — killed for restart\n--- "
                    f"stderr tail ---\n{worker.stderr_tail()}"
                ) from exc

        def on_retry(exc, attempt_n, delay):
            # the restart itself happens in _ensure_worker on the next
            # attempt (and lands the worker:restart event there, with this
            # error as its cause)
            self._worker_last_error[target] = (
                f"{type(exc).__name__}: {exc}"[:300]
            )

        res, worker = self._restart_policy(target).run(
            attempt, retryable=(WorkerUnavailable,),
            describe=f"daemon worker {target}", on_retry=on_retry,
        )
        if not res.get("ok"):
            # the NODE failed inside a healthy worker: same failure class
            # as a fresh process exiting rc!=0 — no restart, route through
            # the (default-off) invoke retry + quorum machinery
            raise RuntimeError(
                f"{script} node failed in worker (pid {res.get('pid')}): "
                f"{res.get('error')}\n--- traceback ---\n"
                f"{str(res.get('traceback', ''))[-4000:]}"
            )
        result = res["result"]
        delta = None
        if isinstance(result, dict) and "cache_delta" in result:
            # warm response: apply the worker's dirty-key delta to the
            # engine-side mirror of its last shipped clean cache — the
            # caller still sees a full "cache" dict (the fresh-process
            # contract at the boundary), without the full re-serialization
            # ever having crossed the pipe
            delta = result.pop("cache_delta") or {}
            base = dict(self._delta_base.get(target) or {})
            base.update(delta.get("set") or {})
            for k in delta.get("del") or ():
                base.pop(k, None)
            result["cache"] = base
            self._delta_base[target] = dict(base)
        elif isinstance(result, dict) and isinstance(
                result.get("cache"), dict):
            self._delta_base[target] = dict(result["cache"])
        self._warm_gen[target] = self._worker_gen.get(target)
        rec.event(
            "daemon:frame", cat="daemon", target=target, site=target,
            tx_bytes=worker.last_tx, rx_bytes=worker.last_rx,
            delta=delta is not None,
            # satellite telemetry for dinulint --wire --reconcile: which
            # schema lane these frame bytes rode, the worker's own warmth
            # report, and the round the response answered
            payload_kind=("delta" if delta is not None else "json"),
            warm=bool(res.get("warm")),
            round=res.get("round"),
        )
        return result

    # ------------------------------------------------- elastic membership
    def add_site(self, site_id=None, site_args=None, first_input=None):
        """Queue the JOIN, then overlap the joiner's worker bring-up
        (interpreter + imports + backend init — seconds) with the
        admission handshake's round-trip: any worker left over from the
        site's previous incarnation is killed NOW (its live cache is the
        stale state the roster epoch exists to refuse) and a fresh one
        spawns on a background thread, so by activation the join costs
        one full-cache frame instead of a synchronous cold start."""
        sid = super().add_site(site_id, site_args=site_args,
                               first_input=first_input)
        if sid in self._spare_workers:
            # a clean pre-spawned spare (never served an invocation):
            # it IS the fresh incarnation — keep it
            self._spare_workers.discard(sid)
            self._prewarmed.add(sid)
            return sid
        self._discard_worker(sid)
        self._prewarmed.add(sid)
        threading.Thread(
            target=self._prewarm_worker, args=(sid,), daemon=True,
            name=f"prewarm-{sid}",
        ).start()
        return sid

    def _prewarm_worker(self, sid):
        try:
            self._ensure_worker(sid, self.local_script, self._recorder())
        except Exception:  # noqa: BLE001 — activation spawns on demand
            self._prewarmed.discard(sid)

    def _discard_worker(self, sid, shutdown=False):
        """Retire ``sid``'s worker AND its delta-protocol bookkeeping in
        one place: a membership incarnation change must never let a warm
        worker (or the engine-side ``_warm_gen``/``_delta_base`` mirror
        feeding the dirty-key frame protocol) survive into the next life.
        Returns the retired worker (already killed/shut down) or None."""
        with self._worker_lock:
            w = self._workers.pop(sid, None)
        self._warm_gen.pop(sid, None)
        self._delta_base.pop(sid, None)
        if w is not None:
            if shutdown:
                w.shutdown()
            else:
                w.kill()
        return w

    def _activate_joiner(self, s, rec):
        """A joiner's (or rejoiner's) worker must start from the FRESH
        incarnation: a worker left over from the site's dead life still
        holds its live cache, and the warm delta protocol would let it
        silently serve stale state.  :meth:`add_site` already killed the
        stale worker and pre-warmed a clean one (which resumes from the
        fresh JSON cache — its first frame ships the full cache); a
        joiner that arrived outside add_site's pre-warm is killed here so
        the next invocation spawns clean."""
        if s in self._prewarmed:
            self._prewarmed.discard(s)
        else:
            self._discard_worker(s)
        super()._activate_joiner(s, rec)

    def _finalize_leavers(self, site_outs, rec):
        """A gracefully left site's warm worker has served its last
        invocation: orderly shutdown, not a corpse for close() to find."""
        before = set(self.left_sites)
        super()._finalize_leavers(site_outs, rec)
        for s in sorted(self.left_sites - before):
            w = self._discard_worker(s, shutdown=True)
            if w is not None:
                rec.event(Daemon.EVENT_SHUTDOWN, cat="daemon",
                          target=str(s), site=str(s), pid=w.pid)

    def _relay_broadcast(self, rnd, rec):
        super()._relay_broadcast(rnd, rec)
        if self.chaos.enabled:
            # idle-kill drill point: the worker dies BETWEEN rounds (during
            # the relay), so the next round's first request finds it dead
            # and the supervisor restarts it.  Check AND kill under the
            # worker lock (tier-5 audit): an async pool thread restarting
            # its own straggler may swap the table entry concurrently, and
            # a kill issued on a stale snapshot would consume the plan
            # entry while the fresh worker survives — a silent no-op kill.
            # kill() is signal + reap only; no re-entrant lock risk.
            with self._worker_lock:
                for target, w in sorted(self._workers.items()):
                    if self.chaos.worker_fault(rnd, target, rec,
                                               when="idle") is not None:
                        w.kill()
                        self._worker_last_error[target] = (
                            "chaos worker_kill (idle)"
                        )

    # -------------------------------------------------------------- lifetime
    def worker_pids(self):
        """{target: pid} of the currently-live workers (test/ops surface:
        a warm run keeps one pid per target for its whole lifetime).
        Snapshot under the worker lock — an async pool thread's restart
        mutates the table concurrently (tier-5 audit)."""
        with self._worker_lock:
            return {t: w.pid for t, w in self._workers.items() if w.alive()}

    def close(self):
        """Shut every worker down (orderly frame, then SIGKILL).  The
        async invocation pool goes down FIRST (a pending straggler request
        then fails on its dead worker and the supervisor refuses to
        respawn under ``_closing``)."""
        self._closing = True
        super().close()  # the async pool (engine.py); no-op on lockstep
        rec = self._recorder()
        with self._worker_lock:
            workers = sorted(self._workers.items())
            self._workers = {}
        for target, w in workers:
            w.shutdown()
            rec.event(Daemon.EVENT_SHUTDOWN, cat="daemon",
                      target=str(target), site=str(target), pid=w.pid)
        rec.flush()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        for w in getattr(self, "_workers", {}).values():
            try:
                w.kill()
            except Exception:  # noqa: BLE001 — interpreter teardown
                pass


if __name__ == "__main__":
    sys.exit(worker_main())
