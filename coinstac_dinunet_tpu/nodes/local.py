"""COINNLocal — the site-side phase state machine + argument pipeline.

Capability parity with the reference ``distrib/nodes/local.py:25-295``:
constructor holds the hyperparameter defaults; first invocation resolves the
three-tier override (engine/compspec ``input`` > ``<task_id>_args`` >
``<agg_engine>_args`` > constructor defaults) and freezes a ``shared_args``
snapshot for the aggregator; then every invocation advances the phase machine
(INIT_RUNS → NEXT_RUN [+pretrain] → PRE_COMPUTATION → COMPUTATION →
NEXT_RUN_WAITING → SUCCESS).

TPU-first notes: the learner's backward is a compiled scan (no per-batch
Python), fold re-init clears engine state + compiled caches, and the
aggregator broadcasts ``target_batches`` so every site's padded loader runs
equal-length lockstep epochs (replacing the reference's wrap-around sampler).
"""
import os
import shutil
import time
import traceback

from .. import config, telemetry, utils
from ..config.keys import AggEngine, Key, LocalWire, Mode, Phase, RemoteWire
from ..data import COINNDataHandle
from ..parallel import COINNLearner, DADLearner, PowerSGDLearner
from ..resilience import transport as wire_transport
from ..utils import logger

# engine/epoch state cleared on every fold transition
_EPHEMERAL_KEYS = (
    "_powersgd_state", "_rankdad_state", "_ep_averages", "_ep_metrics",
    "_train_state", "cursor", "epoch",
)


class COINNLocal:
    """One federated site (≙ ref ``COINNLocal``)."""

    _ARG_DEFAULTS = dict(
        task_id="task",
        mode=Mode.TRAIN.value,
        batch_size=16,
        local_iterations=1,
        epochs=31,
        validation_epochs=1,
        learning_rate=1e-3,
        load_limit=None,
        load_sparse=False,
        pretrained_path=None,
        pretrain_args=None,
        patience=None,
        num_folds=None,
        split_ratio=None,
        split_files=None,
        monitor_metric="f1",
        metric_direction="maximize",
        log_header="loss|precision,recall,f1,accuracy",
        agg_engine=AggEngine.DSGD.value,
        precision_bits=config.default_precision_bits,
        num_classes=2,
        num_averages=1,
        seed=None,
        verbose=False,
        # opt-in dropout tolerance: freezes into shared_args so the
        # aggregator's quorum policy sees it on EVERY transport, including
        # fresh-process nodes configured via first_input
        site_quorum=None,
        # opt-in watchdog quarantine (telemetry/watchdog.py): a site-
        # attributed anomaly zeroes that site's reduce weight from the round
        # it fires; frozen into shared_args so the aggregator sees it
        quarantine_on_anomaly=None,
        # opt-in k-ary hierarchical tree-reduce fan-in for the aggregator
        # (parallel/reducer.py; Federation.REDUCE_FANIN): streams site
        # payloads in groups of k instead of materializing all n_sites at
        # once; frozen into shared_args so the aggregator sees it on every
        # transport
        reduce_fanin=None,
        # opt-in staleness-bounded async rounds (Federation.ASYNC_* keys,
        # engine.py::_step_round_async): k lets a straggler's last
        # contribution stand in for up to k rounds; the pool bounds
        # concurrent site invocations; the discount decays a stale
        # contribution's reduce weight per round of lag.  Frozen into
        # shared_args so the aggregator's window check and the reducer's
        # weighting see the SAME bound the engine enforces
        async_staleness=None,
        async_invoke_pool=None,
        async_stale_discount=None,
        # opt-in run-ahead pipelining depth d (Federation.RUN_AHEAD,
        # engine.py::_step_round_async): the reduce+relay tail runs on a
        # dedicated reducer worker while committed sites are immediately
        # re-submitted up to d broadcasts deep; frozen into shared_args so
        # the aggregator's window check widens to k + d
        run_ahead=None,
        # engine-specific knobs (present so they freeze into shared_args)
        matrix_approximation_rank=1,
        start_powerSGD_iter=10,
        dad_reduction_rank=10,
        dad_num_pow_iters=5,
        dataloader_args=None,
    )

    def __init__(self, cache=None, input=None, state=None, **kw):
        self.out = {}
        self.cache = cache if cache is not None else {}
        self.input = utils.FrozenDict(input or {})
        self.state = utils.FrozenDict(state or {})
        self._args = dict(self._ARG_DEFAULTS)
        for k, v in kw.items():
            self._args[k] = v  # constructor overrides become new defaults
        if not self.cache.get(Key.ARGS_CACHED):
            self._resolve_args()
            self.cache[Key.ARGS_CACHED.value] = True

    # ----------------------------------------------------------- arg pipeline
    def _resolve_args(self):
        """Three-tier override, highest priority last
        (≙ ref ``local.py:92-118``)."""
        args = dict(self._args)
        task_id = self.input.get("task_id", args.get("task_id"))
        args.update(self.input.get(f"{args.get('agg_engine')}_args", {}) or {})
        args.update(self.input.get(f"{task_id}_args", {}) or {})
        for k in self._args:
            if k in self.input:
                args[k] = self.input[k]
        data_conf = self.input.get(
            f"{task_id}_data_conf", self.input.get("data_conf", {})
        )
        self.cache.update(args)
        self.cache["data_conf"] = dict(data_conf or {})
        if self.cache.get("seed") is None:
            self.cache["seed"] = config.current_seed
        self.cache.setdefault("cursor", 0)
        self.cache.setdefault("epoch", 0)

    # ------------------------------------------------------------ phase logic
    def _init_runs(self, trainer):
        """Create splits, probe data sizes, share frozen args
        (≙ ref ``local.py:120-131``)."""
        import json

        out = {}
        trainer.data_handle.prepare_data()
        self.cache["num_folds"] = len(self.cache["splits"])
        out[LocalWire.DATA_SIZE.value] = {}
        for k, sp in self.cache["splits"].items():
            with open(os.path.join(self.cache["split_dir"], sp)) as f:
                split = json.load(f)
            out[LocalWire.DATA_SIZE.value][k] = {key: len(split.get(key, [])) for key in split}
        frozen = {k: self.cache.get(k) for k in self._args}
        frozen["num_folds"] = self.cache["num_folds"]
        self.cache["frozen_args"] = frozen
        out[LocalWire.SHARED_ARGS.value] = utils.clean_recursive(frozen)
        return out

    def _next_run(self, trainer):
        """Per-fold re-initialization (≙ ref ``local.py:133-150``)."""
        out = {}
        for k in _EPHEMERAL_KEYS:
            self.cache.pop(k, None)
        self.cache.update(cursor=0, epoch=0)
        self.cache[Key.TRAIN_SERIALIZABLE.value] = []
        self.cache["split_file"] = self.cache["splits"][str(self.cache["split_ix"])]
        self.cache["log_dir"] = os.path.join(
            self.state.get("outputDirectory", "."),
            str(self.cache["task_id"]),
            f"fold_{self.cache['split_ix']}",
        )
        os.makedirs(self.cache["log_dir"], exist_ok=True)
        tag = f"{self.cache['task_id']}-{self.cache['split_ix']}"
        self.cache["best_nn_state"] = f"best.{tag}.ckpt"
        self.cache["latest_nn_state"] = f"latest.{tag}.ckpt"
        trainer.init_nn()
        out[LocalWire.PHASE.value] = Phase.COMPUTATION.value
        return out

    def _join_run(self, trainer, admission):
        """Mid-run admission (ISSUE 15, :mod:`~..federation.membership`):
        enter the federation at the steady-state COMPUTATION phase without
        replaying the fold lifecycle.  The admission record (broadcast as
        :attr:`~..config.keys.RemoteWire.ADMISSIONS`) carries the current
        fold assignment + ``target_batches`` + the donor's round-alignment
        sync (cursor/epoch/mode), so this site's padded loader falls into
        lockstep mid-epoch; the warm start loads the donor's live weights
        relayed through the existing pretrain-broadcast path
        (``pretrained_weights``) — params AND optimizer state, so the
        joiner's next update application stays bitwise on the replicated
        trajectory.  Local data prep (splits) runs here exactly once: the
        INIT_RUNS work this site never saw, minus the wire."""
        out = {}
        admission = dict(admission)
        self.cache["joined_epoch"] = admission.pop(
            RemoteWire.ROSTER_EPOCH.value, None
        )
        self.cache.update(
            {k: v for k, v in admission.items() if v is not None}
        )
        trainer.data_handle.prepare_data()
        self.cache["num_folds"] = len(self.cache["splits"])
        frozen = {k: self.cache.get(k) for k in self._args}
        frozen["num_folds"] = self.cache["num_folds"]
        self.cache["frozen_args"] = frozen
        self.cache.setdefault("cursor", 0)
        self.cache.setdefault("epoch", 0)
        self.cache[Key.TRAIN_SERIALIZABLE.value] = []
        self.cache["split_file"] = self.cache["splits"][
            str(self.cache["split_ix"])
        ]
        self.cache["log_dir"] = os.path.join(
            self.state.get("outputDirectory", "."),
            str(self.cache["task_id"]),
            f"fold_{self.cache['split_ix']}",
        )
        os.makedirs(self.cache["log_dir"], exist_ok=True)
        tag = f"{self.cache['task_id']}-{self.cache['split_ix']}"
        self.cache["best_nn_state"] = f"best.{tag}.ckpt"
        self.cache["latest_nn_state"] = f"latest.{tag}.ckpt"
        trainer.init_nn()
        wfile = self.input.get(RemoteWire.PRETRAINED_WEIGHTS.value)
        src = (os.path.join(self.state.get("baseDirectory", "."), wfile)
               if wfile else None)
        if src and os.path.exists(src):
            # full train state (params + optimizer + step/rng): the warm
            # start must land ON the federation's replicated trajectory,
            # not merely near it — load_optimizer stays True here, unlike
            # the fold-start pretrain broadcast where everyone is fresh
            trainer.load_checkpoint(full_path=src, allow_torch=False)
            self.cache["_train_state"] = trainer.train_state
        else:
            logger.warn(
                f"joining site {self.state.get('clientId')} found no "
                "warm-start weights broadcast; entering from a fresh init "
                "(the federation's params replication invariant is broken "
                "until convergence re-absorbs it)"
            )
        out[LocalWire.PHASE.value] = Phase.COMPUTATION.value
        return out

    def _pretrain_local(self, trainer):
        """Designated site trains locally and ships its best weights
        (≙ ref ``local.py:152-170``)."""
        out = {LocalWire.PHASE.value: Phase.COMPUTATION.value}
        pretrain_args = self.cache.get("pretrain_args") or {}
        epochs = int(pretrain_args.get("epochs", 0))
        any_pretrains = epochs > 0 and any(
            r.get("pretrain") for r in self.input.get(RemoteWire.GLOBAL_RUNS.value, {}).values()
        )
        if epochs > 0 and self.cache.get("pretrain"):
            saved = {
                k: self.cache.get(k) for k in ("epochs", "pretrain")
            }
            self.cache.update(pretrain_args)
            self.cache["pretrain"] = True
            with telemetry.get_active().span(
                "local:pretrain", cat="train", epochs=epochs
            ):
                trainer.train_local(
                    trainer.data_handle.get_train_dataset(),
                    trainer.data_handle.get_validation_dataset(),
                )
            self.cache.update({k: v for k, v in saved.items() if v is not None})
            # advertise the shipped best weights so the aggregator broadcasts
            if self.cache.get("weights_file"):
                out[LocalWire.WEIGHTS_FILE.value] = self.cache["weights_file"]
            out[LocalWire.PHASE.value] = Phase.PRE_COMPUTATION.value
        if any_pretrains:
            out[LocalWire.PHASE.value] = Phase.PRE_COMPUTATION.value
        return out

    # ----------------------------------------- fresh-process round survival
    # The reference assumes a PERSISTENT node process (live nn/optimizer in
    # cache, ref ``trainer.py:18-20``) — an engine that spawns a fresh
    # process per invocation would silently re-init mid-run there.  Here:
    # with ``cache['persist_round_state']`` every invocation's live state
    # (train state + the compression engine's mid-protocol fields, e.g.
    # PowerSGD's Ms/Phats between the P-sync and Q-sync invocations) writes
    # to disk and transparently restores next invocation; without it, a
    # mid-run invocation that lost the live state FAILS LOUDLY instead of
    # silently re-initializing (see ``compute``).
    def _round_state_path(self):
        return os.path.join(
            self.state.get("outputDirectory", "."), ".round_state.ckpt"
        )

    def _persist_round_state(self, trainer):
        if not self.cache.get("persist_round_state"):
            return
        if trainer.train_state is None:
            return
        extra = {}
        psgd = self.cache.get("_powersgd_state")
        if psgd is not None:
            extra["powersgd"] = psgd.serialize(full=True)
        # the epoch-level train-score accumulators span many rounds (popped
        # at the epoch barrier) — raw-count payloads, exact across restarts
        ep_a = self.cache.get("_ep_averages")
        ep_m = self.cache.get("_ep_metrics")
        if ep_a is not None:
            extra["ep_averages"] = ep_a.serialize()
        if ep_m is not None:
            extra["ep_metrics"] = ep_m.serialize()
        trainer.save_checkpoint(
            full_path=self._round_state_path(), extra=extra
        )

    def _restore_round_state(self, trainer):
        """Rebuild the live train state (and mid-protocol engine state) from
        the previous invocation's round file.  Returns True on success."""
        from .. import parallel
        from ..utils import tensorutils

        path = self._round_state_path()
        if not (self.cache.get("persist_round_state") and os.path.exists(path)):
            return False
        try:
            trainer.init_nn(init_weights=False, init_optimizer=False)
            trainer._init_optimizer()
            trainer._init_train_state()
            trainer.load_checkpoint(full_path=path)
        except Exception as exc:  # noqa: BLE001 — corrupt round file
            logger.warn(f"Unreadable round state {path} ({exc})")
            return False
        extra = getattr(trainer, "last_checkpoint_extra", {})
        if "powersgd" in extra:
            self.cache["_powersgd_state"] = (
                parallel.powersgd._PowerSGDState.deserialize(extra["powersgd"])
            )
        if "ep_averages" in extra:
            shell = trainer.new_averages()
            self.cache["_ep_averages"] = type(shell).deserialize(
                tensorutils.aslist(extra["ep_averages"])
            )
        if "ep_metrics" in extra:
            shell = trainer.new_metrics()
            self.cache["_ep_metrics"] = type(shell).deserialize(
                tensorutils.aslist(extra["ep_metrics"])
            )
        self.cache["_train_state"] = trainer.train_state
        return True

    def _midrun_state_lost(self):
        """True when this invocation is mid-run but the live state is gone —
        the silent-reinit hazard a fresh-process engine hits."""
        return (
            int(self.cache.get("epoch", 0) or 0) > 0
            or int(self.cache.get("cursor", 0) or 0) > 0
            or bool(self.cache.get(Key.TRAIN_SERIALIZABLE.value))
        )

    # ------------------------------------------------------- mid-run resume
    def _resume_pointer(self):
        return os.path.join(
            self.state.get("outputDirectory", "."), ".resume.json"
        )

    def _barrier_autosave(self, trainer):
        """Write a full site resume point at the epoch barrier: latest
        checkpoint (params/opt/step/rng) + the JSON-able cache snapshot +
        carried engine state (PowerSGD error feedback/Qs/warm-up counter —
        ref state contract ``distrib/powersgd/__init__.py:41-48``; the
        rankDAD plan is a pure function of (model, batch shape) and is
        re-derived on first use, so it needs no serialization).

        Cadence/opt-out via ``cache['autosave_epochs']`` (0 disables) — the
        checkpoint write is blocking I/O on the training path."""
        import json

        every = int(self.cache.get("autosave_epochs", 1) or 0)
        if every <= 0 or int(self.cache.get("epoch", 0)) % every != 0:
            return
        snapshot = {
            k: v for k, v in dict(self.cache).items()
            if not str(k).startswith("_") and k != "resume"
        }
        extra = {"site_cache": utils.clean_recursive(snapshot)}
        psgd = self.cache.get("_powersgd_state")
        if psgd is not None:
            extra["powersgd"] = psgd.serialize()
        path = trainer.save_checkpoint(
            name=self.cache["latest_nn_state"], extra=extra
        )
        utils.atomic_write(self._resume_pointer(), json.dumps({"checkpoint": path}))

    def _try_resume(self, trainer):
        """Fresh-cache COMPUTATION invocation with ``resume`` set: rebuild the
        site from the last epoch-barrier autosave.  Returns True on success."""
        import json

        from .. import parallel

        ptr = self._resume_pointer()
        if not os.path.exists(ptr):
            return False
        try:
            with open(ptr) as f:
                ckpt = json.load(f)["checkpoint"]
            if not os.path.exists(ckpt):
                return False
            trainer.init_nn()
            trainer.load_checkpoint(full_path=ckpt)
        except Exception as exc:  # noqa: BLE001 — corrupt resume point
            logger.warn(
                f"Unreadable resume point {ptr} ({exc}); starting fresh"
            )
            return False
        extra = getattr(trainer, "last_checkpoint_extra", {})
        snapshot = dict(extra.get("site_cache", {}))
        snapshot.pop("resume", None)
        self.cache.update(snapshot)
        if "powersgd" in extra:
            self.cache["_powersgd_state"] = (
                parallel.powersgd._PowerSGDState.deserialize(extra["powersgd"])
            )
        self.cache["_train_state"] = trainer.train_state
        logger.info(
            f"Resumed site from {ckpt} (epoch {self.cache.get('epoch')})",
            self.cache.get("verbose", True),
        )
        return True

    def _get_learner_cls(self, learner_cls=None):
        engine = str(self.cache.get("agg_engine"))
        builtin = {
            AggEngine.DSGD.value: COINNLearner,
            AggEngine.RANK_DAD.value: DADLearner,
            AggEngine.POWER_SGD.value: PowerSGDLearner,
        }
        return builtin.get(engine, learner_cls or COINNLearner)

    # -------------------------------------------------------------- main loop
    def compute(self, mp_pool=None, trainer_cls=None, dataset_cls=None,
                datahandle_cls=COINNDataHandle, learner_cls=None, **kw):
        # the real engine runs each invocation in a fresh process; an
        # on-disk compile cache makes round 2+ skip the XLA compile
        utils.maybe_enable_compilation_cache(self.cache)
        trainer = trainer_cls(
            cache=self.cache, input=self.input, state=self.state,
            data_handle=datahandle_cls(
                cache=self.cache, input=self.input, state=self.state,
                dataset_cls=dataset_cls,
                dataloader_args=self.cache.get("dataloader_args"),
            ),
        )

        self.out[LocalWire.PHASE.value] = self.input.get(RemoteWire.PHASE.value, Phase.INIT_RUNS.value)
        if self.out[LocalWire.PHASE.value] == Phase.INIT_RUNS.value:
            self.out.update(**self._init_runs(trainer))

        elif self.out[LocalWire.PHASE.value] == Phase.NEXT_RUN.value:
            self.cache.update(
                **self.input[RemoteWire.GLOBAL_RUNS.value][self.state.get("clientId", "site")]
            )
            self.out.update(**self._next_run(trainer))
            if self.cache.get("mode") == Mode.TRAIN.value:
                self.out.update(**self._pretrain_local(trainer))

        elif self.out[LocalWire.PHASE.value] == Phase.PRE_COMPUTATION.value:
            if self.input.get(RemoteWire.PRETRAINED_WEIGHTS.value):
                trainer.init_nn()
                trainer.load_checkpoint(
                    full_path=os.path.join(
                        self.state.get("baseDirectory", "."),
                        self.input[RemoteWire.PRETRAINED_WEIGHTS.value],
                    ),
                    load_optimizer=False,
                    # aggregator-broadcast file: must be this framework's own
                    # msgpack checkpoint — never route it into torch.load
                    allow_torch=False,
                )
                self.cache["_train_state"] = trainer.train_state
            self.out[LocalWire.PHASE.value] = Phase.COMPUTATION.value

        # mid-run admission (ISSUE 15): a joiner's very first invocation
        # arrives at the steady-state COMPUTATION phase carrying its
        # admission record — adopt it (fold assignment, cursor sync, warm
        # start) before the train-state restoration logic runs.  The
        # split_file guard makes the entry exactly-once: every already-
        # initialized member (and any retry after a completed join) skips.
        admission = (self.input.get(RemoteWire.ADMISSIONS.value) or {}).get(
            self.state.get("clientId", "site")
        )
        if admission is not None and not self.cache.get("split_file"):
            self.out.update(**self._join_run(trainer, admission))

        if self.out[LocalWire.PHASE.value] == Phase.COMPUTATION.value and trainer.train_state is None:
            # later invocations within a fold: models are stateless flax defs;
            # the live train-state pytree persists in the cache (≙ the ref
            # sharing nn/optimizer via cache, ``trainer.py:18-20``)
            if "_train_state" in self.cache:
                trainer.init_nn(init_weights=False, init_optimizer=False)
                trainer._init_optimizer()
                trainer.train_state = self.cache["_train_state"]
            elif self._restore_round_state(trainer):
                pass  # fresh-process engine: rebuilt from the round file
            elif self.cache.get("resume") and self._try_resume(trainer):
                pass  # rebuilt from the epoch-barrier autosave
            elif self._midrun_state_lost():
                # a fresh-process engine without persist_round_state would
                # silently re-initialize mid-run here — refuse instead
                raise RuntimeError(
                    "mid-run invocation (epoch="
                    f"{self.cache.get('epoch')}, cursor="
                    f"{self.cache.get('cursor')}) but the live train state "
                    "is gone — this engine runs each invocation in a fresh "
                    "process.  Set cache['persist_round_state']=true (per-"
                    "round on-disk state, DEPLOY.md §3) or run the node in "
                    "a persistent process; cache['resume']=true recovers "
                    "from the last epoch-barrier autosave only."
                )
            else:
                trainer.init_nn()

        learner = self._get_learner_cls(learner_cls)(trainer=trainer, mp_pool=mp_pool)
        client_id = self.state.get("clientId", "site")
        global_modes = self.input.get(RemoteWire.GLOBAL_MODES.value, {})
        # a site absent from a non-empty uniform broadcast map (a joiner —
        # the map was keyed from the round BEFORE its admission) follows
        # the federation's consensus mode, not its stale constructor
        # default: a joiner entering on a barrier round must barrier too
        mode_fallback = self.cache.get("mode")
        if global_modes and client_id not in global_modes:
            modes = set(global_modes.values())
            if len(modes) == 1:
                mode_fallback = next(iter(modes))
        self.out[LocalWire.MODE.value] = global_modes.get(client_id, mode_fallback)
        # echo the aggregator's round stamp verbatim (idempotent under
        # invocation retries): a delayed duplicate of an earlier message
        # echoes a stale counter, which is how the aggregator rejects it
        # (COINNRemote._check_lockstep_phases / proto-model-stale-contribution)
        if self.input.get(RemoteWire.ROUND.value) is not None:
            self.out[LocalWire.ROUND.value] = self.input[RemoteWire.ROUND.value]
        # ... and the roster epoch alongside it (ISSUE 15): a redelivery
        # out of a previous incarnation echoes the epoch of its dead life,
        # which is how the membership filter refuses it
        # (federation/membership.py / proto-model-roster)
        if self.input.get(RemoteWire.ROSTER_EPOCH.value) is not None:
            self.out[LocalWire.ROSTER_EPOCH.value] = self.input[
                RemoteWire.ROSTER_EPOCH.value
            ]

        rec = telemetry.get_active()
        if self.out[LocalWire.PHASE.value] == Phase.COMPUTATION.value:
            if self.input.get(RemoteWire.SAVE_CURRENT_AS_BEST.value):
                trainer.save_checkpoint(name=self.cache["best_nn_state"])

            if self.input.get(RemoteWire.UPDATE.value):
                with rec.span("local:step", cat="update"):
                    self.out.update(**learner.step())

            if any(m == Mode.TRAIN.value for m in global_modes.values()) or (
                not global_modes and self.out[LocalWire.MODE.value] == Mode.TRAIN.value
            ):
                with rec.span("local:to_reduce", cat="backward"):
                    self.out.update(**learner.to_reduce())

            # engine-brokered membership hooks (ISSUE 15; engine-provided
            # input keys, see config/keys.py ENGINE_PROVIDED_KEYS):
            # ``membership_sync`` asks this member to ship its live train
            # state (params + optimizer, post-update) for a joiner's warm
            # start — it rides the existing weights_file→pretrained_weights
            # broadcast path; ``leave`` flags this round's contribution as
            # the site's graceful last one (the reducer counts it, then the
            # aggregator retires the site — never a site_died)
            if self.input.get("membership_sync") and (
                trainer.train_state is not None
            ):
                sync_name = f"member_sync.{self.cache['task_id']}.ckpt"
                trainer.save_checkpoint(full_path=os.path.join(
                    self.state.get("transferDirectory", "."), sync_name
                ))
                self.out[LocalWire.WEIGHTS_FILE.value] = sync_name
            if self.input.get("leave"):
                self.out[LocalWire.LEAVING.value] = True

            if global_modes and all(
                m == Mode.VALIDATION.value for m in global_modes.values()
            ):
                self.out.update(**trainer.validation_distributed())
                self.out.update(**learner.train_serializable())
                self.out[LocalWire.MODE.value] = Mode.TRAIN_WAITING.value
                # full site resume point at every epoch barrier (params,
                # optimizer, rng, cache snapshot, compression-engine state)
                self._barrier_autosave(trainer)

            if global_modes and all(
                m == Mode.TEST.value for m in global_modes.values()
            ):
                self.out.update(**trainer.test_distributed())
                self.out[LocalWire.MODE.value] = self.cache["frozen_args"]["mode"]
                self.out[LocalWire.PHASE.value] = Phase.NEXT_RUN_WAITING.value
                # _autosave (not a bare save) keeps the epoch/log record a
                # later cache['resume'] train_local needs
                trainer._autosave(len(self.cache.get("train_log", [])))
                utils.save_cache(self.cache, {"outputDirectory": self.cache["log_dir"]})

        elif self.out[LocalWire.PHASE.value] == Phase.SUCCESS.value:
            zip_name = self.input.get(RemoteWire.RESULTS_ZIP.value)
            if zip_name:
                src = os.path.join(
                    self.state.get("baseDirectory", "."), f"{zip_name}.zip"
                )
                dst = os.path.join(
                    self.state.get("outputDirectory", "."), f"{zip_name}.zip"
                )
                for i in range(3):  # relay may lag; poll briefly (ref :267-274)
                    time.sleep(i)
                    if os.path.exists(src):
                        os.makedirs(os.path.dirname(dst), exist_ok=True)
                        shutil.copy(src, dst)
                        break

        # health reporting: ship this site's watchdog summary to the
        # aggregator and surface any federation-wide warning it broadcast
        # (both wire keys declared in config/keys.py; observe-and-report —
        # see telemetry/watchdog.py)
        if rec.enabled:
            fed_health = self.input.get(RemoteWire.HEALTH.value)
            if fed_health and client_id in (fed_health.get("quarantined") or []):
                logger.warn(
                    f"aggregator quarantined this site ({client_id}): its "
                    "payloads carry weight 0 in every reduce "
                    "(cache['quarantine_on_anomaly'])"
                )
            summary = telemetry.Watchdog(self.cache, rec).summary()
            if summary:
                self.out[LocalWire.HEALTH.value] = summary

        # persist the live train state across engine invocations (in cache
        # for a persistent process; on disk for a fresh-process engine)
        if trainer.train_state is not None:
            self.cache["_train_state"] = trainer.train_state
        self._persist_round_state(trainer)
        # async wire commits (cache['async_wire_commit']) must land — or
        # fail THIS invocation loudly — before the output JSON names them
        wire_transport.flush_async()
        return self.out

    def __call__(self, *a, **kw):
        # telemetry: per-phase spans + wire accounting land in per-node
        # JSONL (and cache['profile_stats'], dumped to logs.json) when
        # cache['profile'] is set — the structured successor to the
        # realtime profiling the reference delegates to its engine
        # (SURVEY §5); see docs/TELEMETRY.md
        phase = self.input.get(RemoteWire.PHASE.value, Phase.INIT_RUNS.value)
        rec = telemetry.Recorder.for_node(
            self.cache, self.state, node=self.state.get("clientId", "site")
        )
        rec.begin_invocation(phase=str(phase))
        try:
            with telemetry.activate(rec), rec.span(
                f"local:{phase}", cat="node"
            ):
                self.compute(*a, **kw)
            # "cache" carries the JSON-able node cache back to engines that
            # round-trip it between fresh-process invocations (the live
            # ``_``-prefixed pytrees stay process-local by design)
            return {
                "output": self.out,
                "cache": utils.clean_recursive({
                    k: v for k, v in dict(self.cache).items()
                    if not str(k).startswith("_")
                }),
            }
        except Exception as exc:
            rec.event(
                "node_error", cat="error",
                error=f"{type(exc).__name__}: {exc}",
            )
            traceback.print_exc()
            raise RuntimeError(
                f"Local node failed ({type(exc).__name__}: {exc}) with "
                f"partial out: {self.out}"
            )
        finally:
            # a failed invocation drains its own pending async commits (and
            # their errors) so they can never be misattributed to the NEXT
            # node this process serves; the success path already flushed
            # loudly at the end of compute()
            for exc in wire_transport.flush_async(raise_errors=False):
                logger.warn(f"async wire commit failed: {exc}")
            rec.flush()
