"""COINNRemote — the aggregator phase state machine.

Capability parity with the reference ``distrib/nodes/remote.py:58-310``:
adopts ``shared_args`` from the first site, builds the fold queue, selects the
pretrain site (max train data), reduces gradients when every site reports
``reduce``, runs the epoch/validation barrier over the ``*_WAITING`` modes,
accumulates cross-site scores with **exact count-merge** (the reference
averages derived scores — SURVEY §2 defects), signals best-checkpoint saves,
early-stops, rotates folds, and finally reduces global test scores, writes
CSVs/plots, and ships a results zip.

TPU-first addition: each fold's ``global_runs`` carries ``target_batches``
(global max batches/epoch) so every site's padded loader runs lockstep
equal-length epochs — replacing the reference's wrap-around padded sampler
with static-shape padding + masking.
"""
import datetime
import math
import os
import shutil
import traceback

from .. import config, telemetry, utils
from ..config.keys import (
    AggEngine,
    Federation,
    GatherMode,
    Key,
    LocalWire,
    Membership,
    Metric,
    Mode,
    Phase,
    RemoteWire,
)
from ..data import EmptyDataHandle
from ..parallel import COINNReducer, DADReducer, PowerSGDReducer
from ..resilience import transport as wire_transport
from ..utils import logger
from ..utils.logger import lazy_debug
from ..utils.utils import performance_improved_, stop_training_
from ..vision import plotter
from . import check, gather


class COINNRemote:
    """The aggregator node (≙ ref ``COINNRemote``)."""

    def __init__(self, cache=None, input=None, state=None, verbose=False, **kw):
        self.out = {}
        self.cache = cache if cache is not None else {}
        self.cache.update(**kw)
        self.input = utils.FrozenDict(input or {})
        self.state = utils.FrozenDict(state or {})
        self.cache.setdefault("verbose", verbose)
        if not self.cache.get(Key.ARGS_CACHED) and self.input:
            site = next(iter(self.input.values()))
            if LocalWire.SHARED_ARGS.value in site:
                self.cache.update(**site[LocalWire.SHARED_ARGS.value])
                self.cache[Key.ARGS_CACHED.value] = True

    # ------------------------------------------------------ elastic membership
    def _check_membership(self):
        """Elastic-membership round processing (ISSUE 15,
        :mod:`~..federation.membership`), run BEFORE the quorum check and
        before any reducer/trainer snapshots ``self.input`` — the same
        ordering contract the quorum filtering pins:

        1. drain the engine's join/rejoin request queue
           (``cache['membership_requests']``) into admission records —
           one roster-epoch bump per joiner, broadcast this round as
           :attr:`~..config.keys.RemoteWire.ADMISSIONS`; a pending
           admission also reuses the pretrain-broadcast path
           (:meth:`_pre_compute`) to relay the donor's shipped live
           weights (``weights_file``) to the joiner's warm start;
        2. refuse payloads **by roster epoch**: a non-member's output, or
           an echo of :attr:`~..config.keys.LocalWire.ROSTER_EPOCH` older
           than the site's current admission, is a redelivery out of a
           previous incarnation — dropped from the round exactly as the
           quorum filter drops a reappeared dead site, never aggregated.

        Graceful-leave retirement runs at the END of compute
        (:func:`~..federation.membership.retire_leaving`): the leaver's
        flagged final contribution must first be counted by the reduce.
        """
        from ..federation import membership as _membership

        # the epoch gate runs FIRST: a still-unadmitted rejoiner's stale
        # payload must be judged against the roster as it stood when the
        # payload was sent, and an arriving joiner's first contribution
        # ends its joining grace (clearing the retry-safety ``pending``
        # record) before the re-broadcast below would redundantly ship it
        filtered, refused = _membership.filter_membership(
            self.cache, dict(self.input)
        )
        if refused:
            self.input = utils.FrozenDict(filtered)
        self._admissions = _membership.process_admissions(self.cache)
        if self._admissions:
            self.out[RemoteWire.ADMISSIONS.value] = self._admissions
            # warm start (the existing pretrain-broadcast path): the
            # engine asked a donor member to ship its live weights in the
            # same round it queued the admission.  Re-runs (a retried
            # attempt, a still-joining re-broadcast) are safe: the copy is
            # driven by this round's input and no-ops once the donor's
            # shipped checkpoint is out of it.
            self.out.update(**self._pre_compute())

    # ---------------------------------------------------------- site dropout
    @staticmethod
    def _quorum_need(quorum, roster_size):
        """Normalize ``site_quorum`` to a minimum alive-site COUNT.

        Numeric type must never flip the interpretation (compspec UIs
        commonly deliver JSON numbers as floats): any INTEGRAL value >= 1
        (``1``, ``1.0``, ``2.0``) is a site count; a FRACTION must be
        strictly inside (0, 1) and means that share of the ORIGINAL
        roster (``ceil``).  Non-integral values >= 1 (e.g. ``1.5``) and
        values <= 0 are configuration errors, not silent policies."""
        q = float(quorum)
        if 0.0 < q < 1.0:
            return int(math.ceil(q * roster_size))
        if q >= 1.0 and q == int(q):
            return int(q)
        raise ValueError(
            f"site_quorum {quorum!r} is ambiguous: use an integral value "
            ">= 1 for a minimum alive-site count, or a fraction strictly "
            "in (0, 1) for a share of the initial roster"
        )

    def _check_quorum(self):
        """Enforce the site-participation contract at every barrier.

        The reference hard-fails on a silent site — every barrier is an
        all-site check (ref ``remote.py:225-258``), so a site that stops
        reporting wedges or kills the run with no diagnosis.  Default here
        is the same lockstep contract but LOUD: a site missing from the
        round's input raises with the dropped-site list — on EVERY
        invocation, not only the round a site first vanishes, so a
        persisted-cache re-invocation (external engine retry, resume) can
        never silently continue survivor-weighted without a policy.
        Opt-in ``cache['site_quorum']`` (integral value >= 1 = min alive
        sites regardless of int/float type; fraction strictly in (0,1) =
        min alive share of the initial roster — see :meth:`_quorum_need`)
        lets the run continue with the survivors: reductions are already
        participation-weighted (absent sites simply contribute nothing),
        so the math degrades to the survivor average — the documented
        semantics, never a silent re-weighting.  Once dropped, a site
        stays dropped (its mid-round state is gone) unless elastic
        membership re-admits it with a FRESH incarnation
        (:func:`~..federation.membership.process_admissions` clears the
        drop); quorum is judged against the CURRENT roster —
        ``cache['all_sites']`` mirrors the live member list under elastic
        membership (ISSUE 15), and a just-admitted joiner whose first
        contribution is still in flight (the roster's ``joining`` grace
        set) neither counts as dropped nor inflates the need."""
        roster = self.cache.get("all_sites")
        if not roster:
            return
        joining = set(
            (self.cache.get(Membership.ROSTER) or {}).get("joining") or ()
        )
        if joining:
            # the admission takes effect on the wire one round after the
            # broadcast: a joiner absent from this round's input is not
            # yet DROPPED, and the quorum need is judged without it
            roster = [s for s in roster if s not in joining]
            if not roster:
                return
        prev = set(self.cache.get("dropped_sites", []))
        returned = prev & set(self.input.keys())
        if returned:
            # once dropped, a site STAYS dropped: its mid-run state is gone,
            # so a reappearing process is reporting from a stale model —
            # aggregating it would silently corrupt the global average
            logger.warn(
                f"sites {sorted(returned)} reappeared after being dropped; "
                "ignoring their output (their round state is stale)"
            )
            self.input = utils.FrozenDict({
                k: v for k, v in self.input.items() if k not in prev
            })
        alive = set(self.input.keys())
        dropped = sorted((set(roster) - alive) | prev)
        if not dropped:
            return
        quorum = self.cache.get("site_quorum")
        new_drops = sorted(set(dropped) - prev)
        if not new_drops and quorum:
            # nothing new under a configured policy: the drop was already
            # judged (and logged) the round it happened
            return
        if new_drops:
            self.cache["dropped_sites"] = dropped
            # every quorum decision is a timeline event: which sites
            # vanished, who survives, what policy applied (docs/TELEMETRY.md)
            telemetry.get_active().event(
                "quorum:drop", cat="quorum", sites=new_drops,
                alive=sorted(alive), quorum=quorum,
            )
        if not quorum:
            telemetry.get_active().event(
                "quorum:fail", cat="quorum", reason="no site_quorum policy",
                dropped=dropped,
            )
            raise RuntimeError(
                f"sites {dropped} stopped reporting (round input has "
                f"{sorted(alive)} of {roster}).  The default contract is "
                "all-site lockstep (reference-faithful); set "
                "cache['site_quorum'] (min alive count, or fraction of the "
                "initial roster) to let the run continue with survivors."
            )
        need = self._quorum_need(quorum, len(roster))
        if len(alive) < max(need, 1):
            telemetry.get_active().event(
                "quorum:fail", cat="quorum", reason="quorum unmet",
                alive=sorted(alive), need=max(need, 1), dropped=dropped,
            )
            raise RuntimeError(
                f"quorum unmet: {len(alive)} sites alive "
                f"({sorted(alive)}), quorum {quorum} of {len(roster)} "
                f"requires >= {max(need, 1)}; dropped: {dropped}"
            )
        telemetry.get_active().event(
            "quorum:continue", cat="quorum", alive=sorted(alive),
            dropped=dropped,
        )
        logger.warn(
            f"sites {dropped} dropped; continuing with {sorted(alive)} "
            f"(quorum {quorum} satisfied) — aggregates are survivor-"
            "weighted from this round on"
        )

    # ------------------------------------------------------------- run set-up
    def _init_runs(self):
        if self.cache.get("seed") is None:
            self.cache["seed"] = config.current_seed
        # engines pre-seed the full consortium roster (a round-0 death must
        # count against the founding n_sites); standalone deployments fall
        # back to the INIT round's participants.  The roster record
        # (federation/membership.py) is materialized here at epoch 1 —
        # every membership change after INIT bumps it, and
        # cache['all_sites'] mirrors the CURRENT member list from then on
        self.cache.setdefault("all_sites", sorted(self.input.keys()))
        from ..federation import membership as _membership

        roster = _membership.MembershipRoster.load(self.cache)
        if roster is not None:
            roster.save(self.cache)
        self.cache[Key.GLOBAL_TEST_SERIALIZABLE.value] = []
        self.cache["data_size"] = {
            site: site_vars.get(LocalWire.DATA_SIZE.value)
            for site, site_vars in self.input.items()
        }
        self.cache["folds"] = [
            {"split_ix": str(fold), "seed": self.cache["seed"]}
            for fold in range(int(self.cache["num_folds"]))
        ][::-1]

    def _next_run(self, trainer):
        """Pop a fold; build per-site run assignments (≙ ref ``:88-117``)."""
        self.cache["fold"] = self.cache["folds"].pop()
        split_ix = self.cache["fold"]["split_ix"]
        self.cache["log_dir"] = os.path.join(
            self.state.get("outputDirectory", "."),
            str(self.cache["task_id"]),
            f"fold_{split_ix}",
        )
        os.makedirs(self.cache["log_dir"], exist_ok=True)
        self.cache.update(epoch=0, best_val_epoch=0, best_val_score=None)
        self.cache[Key.TRAIN_LOG.value] = []
        self.cache[Key.VALIDATION_LOG.value] = []
        self.cache[Key.TEST_METRICS.value] = []

        train_sizes = {
            # .get twice: a mid-run joiner (ISSUE 15) reaches later fold
            # transitions without an INIT data_size probe — it simply
            # cannot be the pretrain designee and never sets the pace
            site: (self.cache["data_size"].get(site) or {})
            .get(split_ix, {})
            .get("train", 0)
            for site in self.input
        }
        max_data_site = max(train_sizes, key=train_sizes.get)
        # lockstep epochs: every site pads to the global max batches/epoch
        batch_size = int(self.cache.get("batch_size", 16))
        target_batches = max(
            (math.ceil(n / batch_size) for n in train_sizes.values() if n),
            default=1,
        )
        # cached for mid-run admissions: a joiner's admission record must
        # carry the CURRENT fold's lockstep pace (federation/membership.py)
        self.cache["target_batches"] = target_batches
        out = {}
        for site in self.input:
            fold = {**self.cache["fold"]}
            fold["pretrain"] = site == max_data_site
            fold["target_batches"] = target_batches
            out[site] = fold
        return out

    # --------------------------------------------------------- score handling
    def _metric_shells(self, trainer):
        return trainer.new_averages(), trainer.new_metrics()

    def _reduce_serialized(self, trainer, payloads):
        """Exact cross-site reduction of serialized {averages, metrics}."""
        pairs = gather(["averages", "metrics"], payloads, GatherMode.APPEND)
        averages = trainer.new_averages().reduce_sites(pairs["averages"])
        metrics = trainer.new_metrics().reduce_sites(pairs["metrics"])
        return averages, metrics

    def _accumulate_epoch_info(self, trainer):
        train = gather(
            [Key.TRAIN_SERIALIZABLE.value], self.input.values(), GatherMode.EXTEND
        )[Key.TRAIN_SERIALIZABLE.value]
        val = gather(
            [Key.VALIDATION_SERIALIZABLE.value], self.input.values(), GatherMode.EXTEND
        )[Key.VALIDATION_SERIALIZABLE.value]
        t_avg, t_met = self._reduce_serialized(trainer, train)
        v_avg, v_met = self._reduce_serialized(trainer, val)
        return {
            "train_averages": t_avg, "train_metrics": t_met,
            "val_averages": v_avg, "val_metrics": v_met,
        }

    def _on_epoch_end(self, trainer):
        info = self._accumulate_epoch_info(trainer)
        self.cache[Key.TRAIN_LOG.value].append(
            [*info["train_averages"].get(), *info["train_metrics"].get()]
        )
        self._save_if_better(**info)
        self.cache[Key.VALIDATION_LOG.value].append(
            [*info["val_averages"].get(), *info["val_metrics"].get()]
        )
        if lazy_debug(self.cache["epoch"]):
            plotter.plot_progress(
                self.cache, self.cache["log_dir"],
                plot_keys=[Key.TRAIN_LOG.value, Key.VALIDATION_LOG.value],
                epoch=self.cache.get("epoch"),
            )
        return info

    def _save_if_better(self, **info):
        score = info["val_metrics"].extract(self.cache.get("monitor_metric", "f1"))
        rec = telemetry.get_active()
        if rec.enabled:
            # the GLOBAL monitored-metric trajectory — the federation-level
            # stall series (sites record their local ones)
            from ..telemetry import health as _health

            _health.record_val_score(self.cache, score, recorder=rec)
        self.out[RemoteWire.SAVE_CURRENT_AS_BEST.value] = performance_improved_(
            self.cache["epoch"], score, self.cache
        )

    def _next_epoch(self, **info):
        done = self.cache["epoch"] >= int(self.cache.get("epochs", 1))
        if done or stop_training_(self.cache["epoch"], self.cache):
            return Mode.TEST.value
        return Mode.TRAIN.value

    def _on_run_end(self, trainer):
        """Fold finished: reduce + persist its test scores (≙ ref ``:147-172``)."""
        test = gather(
            [Key.TEST_SERIALIZABLE.value], self.input.values(), GatherMode.EXTEND
        )[Key.TEST_SERIALIZABLE.value]
        averages, metrics = self._reduce_serialized(trainer, test)
        self.cache[Key.TEST_METRICS.value].append(
            [*averages.get(), *metrics.get()]
        )
        self.cache[Key.GLOBAL_TEST_SERIALIZABLE.value].append(
            {"averages": averages.serialize(), "metrics": metrics.serialize()}
        )
        plotter.plot_progress(
            self.cache, self.cache["log_dir"],
            plot_keys=[Key.TRAIN_LOG.value, Key.VALIDATION_LOG.value],
            epoch=self.cache.get("epoch"),
        )
        utils.save_scores(
            self.cache, log_dir=self.cache["log_dir"],
            file_keys=[Key.TEST_METRICS.value],
        )
        utils.save_cache(self.cache, {"outputDirectory": self.cache["log_dir"]})

    def _send_global_scores(self, trainer):
        """All folds done: reduce fold scores, write CSV, zip the output
        (≙ ref ``:174-197``)."""
        out = {}
        averages, metrics = self._reduce_serialized(
            trainer, self.cache[Key.GLOBAL_TEST_SERIALIZABLE.value]
        )
        self.cache["global_test_metrics"] = [[*averages.get(), *metrics.get()]]
        task_dir = os.path.join(
            self.state.get("outputDirectory", "."), str(self.cache["task_id"])
        )
        utils.save_scores(
            self.cache, log_dir=task_dir, file_keys=["global_test_metrics"]
        )
        stamp = "_".join(str(datetime.datetime.now()).split(" "))
        out[RemoteWire.RESULTS_ZIP.value] = (
            f"{self.cache['task_id']}_{self.cache.get('agg_engine')}_{stamp}"
        )
        shutil.make_archive(
            os.path.join(self.state.get("transferDirectory", "."), out[RemoteWire.RESULTS_ZIP.value]),
            "zip",
            task_dir,
        )
        return out

    def _set_mode(self, mode=None):
        return {
            site: (mode if mode else site_vars.get(LocalWire.MODE.value, "N/A"))
            for site, site_vars in self.input.items()
        }

    def _pre_compute(self):
        """Broadcast the pretrain site's weights (≙ ref ``:205-215``)."""
        out = {}
        for site, site_vars in self.input.items():
            if site_vars.get(LocalWire.WEIGHTS_FILE.value):
                src = os.path.join(
                    self.state.get("baseDirectory", "."), site,
                    site_vars[LocalWire.WEIGHTS_FILE.value],
                )
                if os.path.exists(src):
                    out[RemoteWire.PRETRAINED_WEIGHTS.value] = f"pretrained_{config.weights_file}"
                    # atomic: no site can ever observe a half-copied broadcast
                    wire_transport.atomic_copy(
                        src,
                        os.path.join(
                            self.state.get("transferDirectory", "."),
                            out[RemoteWire.PRETRAINED_WEIGHTS.value],
                        ),
                    )
                break
        return out

    def _get_reducer_cls(self, reducer_cls=None):
        engine = str(self.cache.get("agg_engine"))
        builtin = {
            AggEngine.DSGD.value: COINNReducer,
            AggEngine.RANK_DAD.value: DADReducer,
            AggEngine.POWER_SGD.value: PowerSGDReducer,
        }
        return builtin.get(engine, reducer_cls or COINNReducer)

    def _check_lockstep_phases(self):
        """Refuse a round whose sites report heterogeneous phases.

        The protocol is all-site lockstep: every surviving site advances
        through the SAME phase each round, so a mixed-phase input can only
        mean a stale or duplicated round message (a delayed site→aggregator
        delivery standing in for the fresh one).  Pre-fix, such a round
        fell through every ``check(all, ...)`` dispatch block and the
        echoed default phase (INIT_RUNS) silently RESET the whole run —
        the ``proto-model-phase-reset`` counterexample the tier-4 model
        checker surfaced (``dinulint --model``, docs/ANALYSIS.md).  Loud is
        the only safe answer: mid-round state cannot be rebuilt from a
        stale message."""
        phases = {
            site_vars.get(LocalWire.PHASE.value)
            for site_vars in self.input.values()
        }
        if len(phases) > 1:
            per_site = {
                site: site_vars.get(LocalWire.PHASE.value)
                for site, site_vars in self.input.items()
            }
            telemetry.get_active().event(
                "quorum:fail", cat="quorum", reason="mixed phases",
                phases=per_site,
            )
            raise RuntimeError(
                f"lockstep phase violation: sites report mixed phases "
                f"{per_site} — a stale or duplicated round message; "
                "refusing to aggregate (a silent fall-through would reset "
                "the run to INIT_RUNS)"
            )
        # a stale message in the COMPUTATION steady state carries the SAME
        # phase as a fresh one — only the echoed round counter
        # (:attr:`RemoteWire.ROUND`, broadcast below, echoed verbatim by
        # every site) tells them apart.  A site echoing an older counter is
        # reporting from a previous round; aggregating its payload would
        # silently double-count a stale gradient contribution.  ``None``
        # echoes are tolerated (first round; pre-ROUND peers).
        #
        # Staleness-bounded async rounds (``Federation.ASYNC_STALENESS``)
        # relax the exact-stamp contract to a WINDOW: an echo lagging by
        # ``1..k`` rounds is a straggler's in-window stand-in (the engine's
        # ``_step_round_async``), accepted and recorded in
        # ``cache['site_staleness']`` so the reducer down-weights it
        # (``parallel/reducer.py::_site_weights``).  Run-ahead pipelining
        # (``Federation.RUN_AHEAD``) widens the window to ``k + d``: a
        # FRESH contribution computed while the reduce tail was still in
        # flight echoes the broadcast it consumed, up to ``d`` behind the
        # stamp — the same ``site_staleness`` record folds that broadcast
        # lag into the reducer's ``gamma**lag`` discount.  Anything older
        # than the combined window — or ahead of the stamp — is still
        # refused loudly: the window bounds the staleness the protocol
        # tolerates, it never repeals at-most-once delivery (the
        # ``staleness_k``/``run_ahead`` actions of ``dinulint --model``
        # check exactly this boundary).
        expected = self.cache.get("wire_round")
        if expected is not None:
            window = int(self.cache.get(Federation.ASYNC_STALENESS) or 0)
            window += int(self.cache.get(Federation.RUN_AHEAD) or 0)
            stale, behind = {}, {}
            for site, site_vars in self.input.items():
                echo = site_vars.get(LocalWire.ROUND.value)
                if echo is None:
                    continue
                lag = int(expected) - int(echo)
                if lag == 0:
                    continue
                if 0 < lag <= window:
                    stale[site] = lag
                else:
                    behind[site] = int(echo)
            if behind:
                telemetry.get_active().event(
                    "quorum:fail", cat="quorum", reason="stale round echo",
                    expected=int(expected), behind=behind, window=window,
                )
                raise RuntimeError(
                    f"lockstep round violation: expected every site to echo "
                    f"round {int(expected)}"
                    + (f" (staleness window {window})" if window else "")
                    + f" but got {behind} — a stale or duplicated site "
                    "message beyond the tolerated window; refusing to "
                    "aggregate its payload into this round's reduce"
                )
            # per-round staleness record (volatile): the reducer's
            # staleness discount and the health broadcast read it; an
            # empty dict every fresh round clears the previous window
            self.cache["site_staleness"] = stale
            if stale:
                rec = telemetry.get_active()
                rec.event(
                    "async:window", cat="async", expected=int(expected),
                    stale=stale, window=window,
                )
                for site, lag in sorted(stale.items()):
                    rec.metric(Metric.SITE_STALENESS, float(lag), site=site)
        # the roster-epoch half of the lockstep contract (ISSUE 15): every
        # echoed ROSTER_EPOCH must be AT MOST the aggregator's current
        # epoch — a site claiming a future roster ("roster_epoch" ahead of
        # the broadcast) can only be a cross-run or forged message and is
        # refused loudly.  Echoes LAGGING the current epoch are legitimate
        # (epoch bumps overtake in-flight rounds); echoes older than the
        # site's own admission were already dropped by the membership
        # filter (federation/membership.py) before this check ran.
        roster_rec = self.cache.get(Membership.ROSTER) or {}
        cur_epoch = roster_rec.get("epoch")
        if cur_epoch is not None:
            ahead = {}
            for site, site_vars in self.input.items():
                echo = site_vars.get(LocalWire.ROSTER_EPOCH.value)
                if echo is not None and int(echo) > int(cur_epoch):
                    ahead[site] = int(echo)
            if ahead:
                telemetry.get_active().event(
                    "quorum:fail", cat="quorum",
                    reason="roster epoch ahead", epoch=int(cur_epoch),
                    ahead=ahead,
                )
                raise RuntimeError(
                    f"roster epoch violation: sites {ahead} echo a roster "
                    f"epoch ahead of the aggregator's ({int(cur_epoch)}) — "
                    "a cross-run or forged membership message; refusing "
                    "to aggregate"
                )

    # -------------------------------------------------------------- main loop
    def compute(self, mp_pool=None, trainer_cls=None, reducer_cls=None, **kw):
        utils.maybe_enable_compilation_cache(self.cache)
        # membership + quorum filtering MUST precede the trainer/reducer
        # construction: both snapshot ``self.input``, so a reappeared
        # dropped site (or a stale incarnation refused by roster epoch)
        # filtered only afterwards would still reach the reduce and its
        # stale payload would be silently double-counted into the global
        # average — the ``proto-model-stale-contribution`` counterexample
        # the tier-4 model checker surfaced (dinulint --model,
        # docs/ANALYSIS.md "Tier 4"; the roster variant is
        # ``proto-model-roster``)
        self._check_membership()
        self._check_quorum()
        self._check_lockstep_phases()
        trainer = trainer_cls(
            cache=self.cache, input=self.input, state=self.state,
            data_handle=EmptyDataHandle(
                cache=self.cache, input=self.input, state=self.state
            ),
        )
        self.out[RemoteWire.PHASE.value] = self.input.get(LocalWire.PHASE.value, Phase.INIT_RUNS.value)

        if check(all, LocalWire.PHASE.value, Phase.INIT_RUNS.value, self.input):
            self._init_runs()
            self.out[RemoteWire.GLOBAL_RUNS.value] = self._next_run(trainer)
            self.out[RemoteWire.PHASE.value] = Phase.NEXT_RUN.value

        if check(all, LocalWire.PHASE.value, Phase.PRE_COMPUTATION.value, self.input):
            self.out.update(**self._pre_compute())
            self.out[RemoteWire.PHASE.value] = Phase.PRE_COMPUTATION.value

        # the lockstep round stamp (checked above): monotonic per
        # SUCCESSFUL aggregator invocation, echoed back verbatim by every
        # site next round.  The stamp rides the output here but commits to
        # the cache only at the END of compute — a failed invocation never
        # broadcast, so an invoke RETRY re-entering compute must still
        # expect the previous value or every retry would trip the lockstep
        # check it can never satisfy.
        self.out[RemoteWire.ROUND.value] = (
            int(self.cache.get("wire_round") or 0) + 1
        )
        # the roster epoch rides every broadcast alongside the round stamp
        # (echoed back verbatim — the membership filter's refusal basis)
        roster_rec = self.cache.get(Membership.ROSTER)
        if isinstance(roster_rec, dict) and "epoch" in roster_rec:
            self.out[RemoteWire.ROSTER_EPOCH.value] = int(roster_rec["epoch"])

        rec = telemetry.get_active()
        self.out[RemoteWire.GLOBAL_MODES.value] = self._set_mode()
        if check(all, LocalWire.PHASE.value, Phase.COMPUTATION.value, self.input):
            reducer = self._get_reducer_cls(reducer_cls)(
                trainer=trainer, mp_pool=mp_pool
            )
            self.out[RemoteWire.PHASE.value] = Phase.COMPUTATION.value
            if check(all, LocalWire.REDUCE.value, True, self.input):
                with rec.span(
                    "remote:reduce", cat="reduce",
                    engine=str(self.cache.get("agg_engine")),
                    sites=len(self.input),
                ):
                    self.out.update(**reducer.reduce())

            if check(all, LocalWire.MODE.value, Mode.VALIDATION_WAITING.value, self.input):
                self.cache["epoch"] += 1
                if self.cache["epoch"] % int(self.cache.get("validation_epochs", 1)) == 0:
                    self.out[RemoteWire.GLOBAL_MODES.value] = self._set_mode(Mode.VALIDATION.value)
                else:
                    self.out[RemoteWire.GLOBAL_MODES.value] = self._set_mode(Mode.TRAIN.value)

            if check(all, LocalWire.MODE.value, Mode.TRAIN_WAITING.value, self.input):
                with rec.span("remote:epoch_end", cat="barrier"):
                    info = self._on_epoch_end(trainer)
                self.out[RemoteWire.GLOBAL_MODES.value] = self._set_mode(self._next_epoch(**info))

        if check(all, LocalWire.PHASE.value, Phase.NEXT_RUN_WAITING.value, self.input):
            with rec.span("remote:run_end", cat="barrier"):
                self._on_run_end(trainer)
            if self.cache["folds"]:
                self.out[RemoteWire.GLOBAL_RUNS.value] = self._next_run(trainer)
                self.out[RemoteWire.PHASE.value] = Phase.NEXT_RUN.value
            else:
                self.out.update(**self._send_global_scores(trainer))
                self.out[RemoteWire.PHASE.value] = Phase.SUCCESS.value

        # graceful-leave retirement (ISSUE 15): AFTER every dispatch block
        # consumed the round's input — the leaver's flagged final
        # contribution was counted by the reduce above, so retiring it now
        # costs nothing (epoch bump, shrunken roster from next round on;
        # never a site_died, never a retry cycle)
        from ..federation import membership as _membership

        _membership.retire_leaving(self.cache, {
            site: site_vars
            for site, site_vars in self.input.items()
            if isinstance(site_vars, dict)
            and site_vars.get(LocalWire.LEAVING.value)
        })

        # federation-wide health rollup: the aggregator's own watchdog
        # findings (reduce-side divergence/nonfinite/stall) merged with
        # every site's shipped summary, broadcast back so each site can
        # surface warnings (and learn it was quarantined)
        if rec.enabled:
            fed = dict(telemetry.Watchdog(self.cache, rec).summary())
            per_site = {}
            caps = {}
            for site, site_vars in self.input.items():
                h = site_vars.get(LocalWire.HEALTH.value)
                if h:
                    entry = {"counts": h.get("counts", {})}
                    # federation-wide utilization: each site's perf
                    # flight-recorder rollup (samples/s, MFU, HBM) rides
                    # the same health broadcast (telemetry/perf.py)
                    if h.get("perf"):
                        entry["perf"] = h["perf"]
                        sps = h["perf"].get("samples_per_sec")
                        if sps:
                            caps[site] = float(sps)
                    per_site[site] = entry
            if caps:
                # observed per-site throughput — the capacity-aware reduce
                # weighting's data source (parallel/reducer.py,
                # cache['capacity_weight']; ROADMAP 3b)
                cap_rec = dict(self.cache.get(Membership.SITE_CAPACITY) or {})
                cap_rec.update(caps)
                self.cache[Membership.SITE_CAPACITY] = cap_rec
            if per_site:
                fed["sites"] = per_site
            if fed:
                self.out[RemoteWire.HEALTH.value] = fed
        # async wire commits must land — or fail loudly — before the output
        # JSON naming the committed broadcast files leaves this node
        wire_transport.flush_async()
        # commit the round stamp LAST: everything above could still fail,
        # and only an invocation that actually returns its output has
        # issued the stamp (mid-round cache write — _VOLATILE_CACHE_KEYS)
        self.cache["wire_round"] = self.out[RemoteWire.ROUND.value]
        return self.out

    def __call__(self, *a, **kw):
        rec = telemetry.Recorder.for_node(self.cache, self.state, node="remote")
        rec.begin_invocation()
        try:
            with telemetry.activate(rec), rec.span("remote:round", cat="node"):
                self.compute(*a, **kw)
            return {
                "output": self.out,
                "success": check(all, LocalWire.PHASE.value, Phase.SUCCESS.value, self.input),
                # JSON-able cache for fresh-process engines (see COINNLocal)
                "cache": utils.clean_recursive({
                    k: v for k, v in dict(self.cache).items()
                    if not str(k).startswith("_")
                }),
            }
        except Exception as exc:
            rec.event(
                "node_error", cat="error",
                error=f"{type(exc).__name__}: {exc}",
            )
            traceback.print_exc()
            raise RuntimeError(
                f"Remote node failed ({type(exc).__name__}: {exc}) with "
                f"partial out: {self.out}"
            )
        finally:
            # drain (never re-raise) pending async commits on failure so one
            # invocation's commit errors cannot leak into the next node
            for exc in wire_transport.flush_async(raise_errors=False):
                logger.warn(f"async wire commit failed: {exc}")
            rec.flush()
