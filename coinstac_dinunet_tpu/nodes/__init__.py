"""Node orchestration: the federated phase state machines.

:class:`COINNLocal` (site) and :class:`COINNRemote` (aggregator) — capability
parity with the reference ``distrib/nodes/`` — drive training through the
INIT_RUNS → NEXT_RUN → PRE_COMPUTATION → COMPUTATION → NEXT_RUN_WAITING →
SUCCESS lifecycle, exchanging JSON control messages and wire files.  The same
vocabulary drives the in-process simulator (:mod:`..engine`) and an external
COINSTAC-style engine.
"""
from ..config.keys import GatherMode


def check(logic, k, v, inputs):
    """``logic`` (all/any) of sites' ``inputs[site][k] == v``
    (≙ ref ``remote.py:51-55``)."""
    return logic(
        str(site_vars.get(k)) == str(v) for site_vars in inputs.values()
    ) if inputs else False


def gather(keys, dicts, mode=GatherMode.APPEND):
    """Collect ``keys`` across a list of dicts (≙ ref ``_gather``,
    ``remote.py:29-48``): APPEND keeps one entry per dict, EXTEND flattens
    list values.  ``mode`` is a :class:`~..config.keys.GatherMode` (the
    reference defines the enum but passes raw strings — ``config/keys.py:
    47-49`` vs ``remote.py:30``, SURVEY §2 defects); plain strings still
    work for wire compatibility."""
    mode = GatherMode(mode)
    out = {k: [] for k in keys}
    for d in dicts:
        for k in keys:
            v = d.get(k)
            if v is None:
                continue
            if mode is GatherMode.EXTEND and isinstance(v, list):
                out[k].extend(v)
            else:
                out[k].append(v)
    return out


from .local import COINNLocal  # noqa: F401,E402
from .remote import COINNRemote  # noqa: F401,E402

__all__ = ["COINNLocal", "COINNRemote", "check", "gather"]
