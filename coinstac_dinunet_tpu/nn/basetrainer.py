"""NNTrainer — the single-node NN runtime, re-designed for JAX/XLA.

Capability parity with the reference ``nn/basetrainer.py:20-326`` (multi-model/
multi-optimizer dicts, seeded init, checkpoint save/load, ``train_local``,
``evaluation``, ``reduce_iteration``, user hooks), with a TPU-first core:

- Training state is a pytree (``TrainState``: params/opt_state/step/rng), not
  mutable modules; the hot loop is ONE jit-compiled pure function per trainer
  (``_train_step``), with ``lax.scan`` over ``local_iterations`` stacked
  micro-batches for gradient accumulation (≙ ref ``:173-184`` step/zero_grad
  cadence — but compiled, no per-batch Python).
- Multi-network schemes keep the dict-of-models API (``nn['name']``), and the
  checkpoint writes ALL models+optimizers (the reference loses all but the
  last: ``nn/basetrainer.py:103-114``).
- Evaluation consumes padded static-shape batches and weighs metrics by the
  loader's ``_mask`` — the jit-friendly replacement for the reference's
  padded sampler.

User subclasses implement ``_init_nn_model`` (build flax modules) and
``iteration(params, batch, rng)`` — a PURE function of its inputs returning at
least ``{'loss': scalar}`` (plus optional ``pred``/``true``/``averages``).
"""
import contextlib
import os
from typing import Any

import numpy as np

import flax
import jax
import jax.numpy as jnp
import optax

from .. import config
from ..config.keys import Key, MeshAxis, Mode
from ..metrics import COINNAverages, Prf1a
from ..telemetry import capture as _capture
from ..telemetry import get_active as _telemetry
from ..telemetry import health as _health
from ..telemetry import perf as _perf
from ..utils import atomic_write, logger
from ..utils.jax_compat import resolve_donate_argnums, shard_map
from ..utils.utils import performance_improved_, stop_training_

CHECKPOINT_SOURCE = "coinstac-dinunet-tpu"

# Process-wide compiled-step cache: bucket key -> {name: jitted fn}.
# The COINSTAC contract rebuilds the node (and its trainer) from scratch on
# EVERY engine invocation; without sharing, each federated round re-traces
# and re-compiles the train step — the dominant file-transport round cost
# (~1–2 s/round on CPU vs ~10 ms of actual compute).  See
# :meth:`NNTrainer._shared_compiled_bucket` for the key contract.
_SHARED_COMPILED = {}

# (class qualname, cache key) pairs already warned about as un-keyable —
# the fresh-trainer-per-invocation contract would repeat the warning every
# federated round otherwise.
_UNKEYABLE_WARNED = set()

# The framework's own round/fold-varying bookkeeping cache keys — exact
# names, every one verified trace-irrelevant (host-side state machine,
# logging, checkpoint names, per-fold seeds/paths).  Leading-underscore
# keys (internal carried state) are excluded by rule.  User cache keys are
# NEVER dropped: an unknown key that varies per round only churns the
# bucket key (recompiles, never a wrong program), while silently dropping
# a trace-relevant user key could share a stale trace.
_VOLATILE_CACHE_KEYS = frozenset((
    "best_nn_state", "best_val_epoch", "best_val_score", "latest_nn_state",
    "cursor", "epoch", "fold", "folds", "mode", "data_size",
    "splits", "split_ix", "split_dir", "split_file", "split_files",
    "skipped_sites", "global_test_metrics", "log_dir", "log_header",
    "resume", "profile_stats", "telemetry_round", "weights_file", "train_log",
    "validation_log", "test_log", "seed", "verbose",
    # watchdog/health bookkeeping: detector state + anomaly rollup mutate
    # every round and the quarantine roster grows — all host-side, never
    # traced (telemetry/watchdog.py)
    "health", "quarantined_sites",
    # wire retry pressure counters (resilience/retry.py) mutate per load —
    # host-side bookkeeping, never trace-relevant
    "wire_retry_stats",
    # the lockstep round stamp (nodes/remote.py broadcast, echoed by every
    # site): increments every aggregator invocation by design — host-side
    # protocol bookkeeping, never traced
    "wire_round",
    # quorum roster bookkeeping (nodes/remote.py): grows the round a site
    # dies — host-side policy state, never traced.  Leaving it keyed would
    # churn the aggregator trainer's shared-bucket key (one recompile per
    # drop event); the proto-cache-volatile tier-3 rule guards this list.
    "dropped_sites",
    # per-round async staleness record (nodes/remote.py window check →
    # parallel/reducer.py discount): rewritten every aggregator round —
    # host-side protocol bookkeeping, never traced
    "site_staleness",
    # elastic-membership state (federation/membership.py, ISSUE 15): the
    # versioned roster record mutates on every join/leave/rejoin, the
    # request queue is drained per aggregator round, site-capacity
    # throughput refreshes from every HEALTH rollup, and the quorum roster
    # mirror tracks the live membership — all host-side protocol
    # bookkeeping, never traced
    "roster", "membership_requests", "site_capacity", "all_sites",
    "target_batches", "joined_epoch",
    # ... and the join entry (nodes/local.py::_join_run) replays the
    # INIT_RUNS bookkeeping mid-round: num_folds derives from the volatile
    # splits record, and frozen_args mirrors arg keys that ALL remain in
    # the bucket key individually — neither write carries trace-relevant
    # information the key does not already see
    "num_folds", "frozen_args",
    # Key.* bookkeeping the nodes append per round/fold (metrics rollups,
    # serialized score blobs, one-shot flags) — all host-side, never traced
    Key.TEST_METRICS.value, Key.TRAIN_SERIALIZABLE.value,
    Key.VALIDATION_SERIALIZABLE.value, Key.TEST_SERIALIZABLE.value,
    Key.GLOBAL_TEST_SERIALIZABLE.value, Key.ARGS_CACHED.value,
    Key.DATA_CURSOR.value,
))


class TrainState(flax.struct.PyTreeNode):
    """Everything the compiled train step reads and writes."""

    params: Any
    opt_state: Any
    step: Any
    rng: Any


def seeded_rng(seed):
    return jax.random.PRNGKey(int(seed))


class NNTrainer:
    """Single-node training runtime over a dict of flax models."""

    # Class-level default for the staging-time input cast (see
    # :meth:`_input_cast_dtype`).  Every shipped model casts inputs to its
    # compute dtype as its first op, so the staging cast is exact for them;
    # a custom trainer whose model does float32 work on RAW inputs should
    # set ``CAST_INPUTS = False`` (or pass ``cache['cast_inputs']=False``).
    CAST_INPUTS = True

    def __init__(self, cache=None, input=None, state=None, data_handle=None, **kw):
        self.cache = cache if cache is not None else {}
        self.input = input if input is not None else {}
        self.state = state if state is not None else {}
        self.data_handle = data_handle
        self.nn = {}  # name -> flax Module
        self.optimizer = {}  # name -> optax GradientTransformation
        self.train_state: TrainState = None
        self._own_compiled = {}  # per-instance fallback (sharing off/not yet bindable)
        self._shared_bucket = None
        self._share_opt_out = False  # permanent: set by the _compiled setter
        self._share_blocked_by_cache = False  # un-keyable cache value; init_nn re-evaluates

    @property
    def _compiled(self):
        """Compiled-step cache — binds to the process-wide shared bucket
        LAZILY, at first use after the param tree exists.  The node state
        machine restores a carried train state AFTER a partial
        ``init_nn(init_weights=False, init_optimizer=False)``, so binding
        eagerly at init time would (and once did) silently fall back to an
        unshared per-instance cache on the steady-state federated path and
        re-compile every round."""
        if self._shared_bucket is not None:
            return self._shared_bucket
        if (self._share_opt_out or self._share_blocked_by_cache
                or not self.cache.get("share_compiled", True)):
            return self._own_compiled
        params = (self.train_state.params if self.train_state is not None
                  else getattr(self, "_params", None))
        if params is None:  # architecture not fingerprintable yet
            return self._own_compiled
        bucket = self._shared_compiled_bucket(params)
        if bucket is None:  # un-keyable cache entry: sharing would be unsafe
            self._share_blocked_by_cache = True
            return self._own_compiled
        self._shared_bucket = bucket
        return self._shared_bucket

    @_compiled.setter
    def _compiled(self, value):
        """Replace the compiled cache (tests / instance-level overrides).
        Assignment opts THIS INSTANCE out of bucket sharing — an
        instance-level override (e.g. a monkeypatched ``iteration``) must
        never trace into, or read from, the shared bucket.  The opt-out is
        an instance attribute, not a cache write: the cache is the node's
        persisted state and outlives this trainer."""
        self._share_opt_out = True
        self._own_compiled = dict(value)
        self._shared_bucket = None

    # ------------------------------------------------------------------ hooks
    def _init_nn_model(self):
        """Populate ``self.nn`` with flax modules (user hook)."""
        raise NotImplementedError

    def example_inputs(self):
        """Per-model example input(s) used to initialize parameters.

        Default: zeros of ``cache['input_shape']`` (excluding batch dim) with
        batch size 1 for every model.  Override for multi-input models.
        """
        from ..utils import parse_shape

        shape = parse_shape(self.cache.get("input_shape"), ())
        if not shape:
            raise NotImplementedError(
                "Provide cache['input_shape'] or override example_inputs()"
            )
        x = jnp.zeros((1, *shape), dtype=jnp.float32)
        return {name: (x,) for name in self.nn}

    def iteration(self, params, batch, rng=None):
        """Pure forward+loss (user hook).  Must return ``{'loss': scalar}``;
        optional keys: ``pred``/``true`` (for metrics), ``averages`` (values
        for :class:`COINNAverages`), anything else is carried through."""
        raise NotImplementedError

    def iteration_sharded(self, params, batch, rng=None, sp_axis=None):
        """Sequence-parallel-aware iteration (hook for the ``(site, sp)``
        mesh, :class:`~..parallel.seq_mesh.SeqMeshFederation`).

        Called inside ``shard_map`` with ``batch['inputs']``'s sequence axis
        sharded over mesh axis ``sp_axis``; the model must attend globally
        (ring attention), offset positional state by its sequence block, and
        reduce any pooling over the axis.  Default: plain ``iteration`` when
        ``sp_axis`` is None, otherwise refuse — silently attending only to
        the local block would change the math, not just the layout."""
        if sp_axis is None:
            return self.iteration(params, batch, rng)
        raise NotImplementedError(
            f"{type(self).__name__} does not implement sequence parallelism; "
            "override iteration_sharded() to run with sequence_parallel > 1"
        )

    def iteration_tp(self, params, batch, rng=None, tp_axis=None):
        """Tensor-parallel-aware iteration (hook for the ``(site, tp)``
        mesh, :class:`~..parallel.tp_mesh.TPMeshFederation`).

        Called inside ``shard_map`` with the site's batch REPLICATED across
        the ``tp`` ranks; the model must compute each heavy matmul's
        rank-slice (Megatron column/row parallelism — ``TPDense`` in
        ``models/transformer.py``) and psum the row-parallel outputs so the
        loss comes out replicated.  Default: plain ``iteration`` when
        ``tp_axis`` is None, otherwise refuse — running the full model on
        every tp rank would silently waste tp× the compute, and slicing
        without the matching collectives would change the math."""
        if tp_axis is None:
            return self.iteration(params, batch, rng)
        raise NotImplementedError(
            f"{type(self).__name__} does not implement tensor parallelism; "
            "override iteration_tp() to run with tensor_parallel > 1"
        )

    def _init_optimizer(self):
        """Default: one Adam per model at ``cache['learning_rate']``."""
        lr = float(self.cache.get("learning_rate", 1e-3))
        for name in self.nn:
            self.optimizer[name] = optax.adam(lr)

    def new_metrics(self):
        return Prf1a()

    def new_averages(self):
        return COINNAverages(num_averages=int(self.cache.get("num_averages", 1)))

    # ------------------------------------------------------------ init / state
    def _shared_compiled_bucket(self, params):
        """Process-wide bucket of compiled step functions for this trainer
        configuration — so the fresh trainer each engine invocation builds
        reuses the previous round's traces instead of recompiling.

        Correctness contract: a compiled step is pure in its (train-state,
        batch) arguments, and everything it bakes in at trace time (model
        wiring, optimizer hyper-parameters, metric classes, dropout rates,
        engine flags) is derived from the trainer class plus cache config.
        The bucket key is (class, param-tree fingerprint, non-volatile
        JSON-able cache entries):

        - the param fingerprint (every leaf's path + shape + dtype) keys the
          architecture directly, so e.g. two FSV trainers with different
          ``hidden_sizes`` can never share a bucket — a retrace inside a
          shared bucket re-binds the FIRST trainer's closed-over model, so
          shape-driven retracing must never cross architectures;
        - the framework's own volatile cache entries (paths, logs,
          counters, seeds, carried state blobs — the exact-name list
          ``_VOLATILE_CACHE_KEYS`` plus leading-underscore keys) never
          influence a trace and are excluded so the key stays stable
          across rounds; every other JSON-serializable value (scalars,
          lists, nested dicts — including any user-added key) is part of
          the key.

        ``cache['share_compiled']=False`` opts out — required for a custom
        trainer whose ``iteration`` bakes in trace-relevant state that is
        neither in the param tree nor a JSON-able cache value (e.g. a numpy
        array of loss weights, or attributes set outside the cache).

        Lifetime note: a bucket's compiled functions keep the trainer that
        traced them (and whatever it references) alive for the process —
        the cache is process-lifetime by design, like jax's own jit cache."""
        import json

        cfg = {}
        for k, v in self.cache.items():
            k = str(k)
            if k in _VOLATILE_CACHE_KEYS or k.startswith("_"):
                continue
            try:
                # sort_keys here too: a dict value with mixed-type keys must
                # fail NOW (→ sharing disabled), not at the final dumps below
                json.dumps(v, sort_keys=True)
            except (TypeError, ValueError):
                # A non-volatile cache entry we cannot key on (e.g. a numpy
                # array of loss weights a custom iteration() reads).  Sharing
                # a compiled step across trainers that differ only in this
                # value would silently reuse a stale trace — disable sharing
                # for this trainer instead of silently dropping the key.
                # Warn once per (class, key) per process: the fresh-trainer-
                # per-round contract would otherwise repeat this every round.
                warn_key = (type(self).__qualname__, k)
                if warn_key not in _UNKEYABLE_WARNED:
                    _UNKEYABLE_WARNED.add(warn_key)
                    logger.warn(
                        f"cache[{k!r}] is not JSON-serializable; compiled-"
                        f"step sharing disabled for {type(self).__qualname__}"
                        " (set cache['share_compiled']=False to silence, or "
                        "store the value under a '_'-prefixed key if it is "
                        "trace-irrelevant)"
                    )
                return None
            cfg[k] = v

        fingerprint = tuple(
            (jax.tree_util.keystr(path), tuple(leaf.shape), str(leaf.dtype))
            for path, leaf in jax.tree_util.tree_leaves_with_path(params)
        )
        # operational env kill-switches are read at trace time too
        cfg["__env_no_s2d__"] = os.environ.get("COINN_NO_S2D", "")
        cfg["__env_no_fused_gn__"] = os.environ.get("COINN_NO_FUSED_GN", "")
        cfg["__env_flash_xla_bwd__"] = os.environ.get("COINN_FLASH_XLA_BWD", "")
        key = (
            type(self).__module__,
            type(self).__qualname__,
            fingerprint,
            json.dumps(cfg, sort_keys=True),
        )
        return _SHARED_COMPILED.setdefault(key, {})

    def init_nn(self, init_models=True, init_weights=True, init_optimizer=True):
        # drop any bucket binding: the config (learning rate, dtype, width)
        # may have changed — the _compiled property re-binds on next use.
        # The cache-driven sharing block is re-evaluated too (the offending
        # value may be gone); only the setter's opt-out is permanent.
        self._own_compiled = {}
        self._shared_bucket = None
        self._share_blocked_by_cache = False
        if init_models:
            self._init_nn_model()
        if init_weights:
            self._init_nn_weights()
        if init_optimizer:
            self._init_optimizer()
            self._init_train_state()
        return self

    def _creation_ordered_params(self):
        """Fresh seeded init of every model — the param tree with dicts in
        CREATION order (kernel before bias, modules in call order).  Trees
        that have been through a jitted step come back key-SORTED, so
        anything that pairs params positionally against an external
        definition order (torch checkpoint import) must use this tree."""
        seed = int(self.cache.get("seed", config.current_seed))
        rng = seeded_rng(seed)
        out = {}
        examples = self.example_inputs()
        for name, module in self.nn.items():
            rng, sub = jax.random.split(rng)
            args = examples[name]
            if not isinstance(args, (tuple, list)):
                args = (args,)
            out[name] = module.init(sub, *args)
        return out

    def _init_nn_weights(self):
        """Seeded parameter init — the same seed at every site makes replicas
        identical by construction (the federated weight-sync invariant, ref
        SURVEY §3.3).  ``pretrained_path`` warm-start wins over fresh init."""
        pretrained = self.cache.get("pretrained_path")
        self._params = self._creation_ordered_params()
        if pretrained:
            self.load_checkpoint(full_path=pretrained, load_optimizer=False)

    def _init_train_state(self):
        params = getattr(self, "_params", None)
        if params is None:
            self._init_nn_weights()
            params = self._params
        opt_state = {n: self.optimizer[n].init(params[n]) for n in params}
        seed = int(self.cache.get("seed", config.current_seed))
        self.train_state = TrainState(
            params=params,
            opt_state=opt_state,
            step=jnp.zeros((), jnp.int32),
            rng=seeded_rng(seed + 1),
        )

    # ------------------------------------------------------------- checkpoints
    def checkpoint_path(self, name=None):
        log_dir = self.cache.get("log_dir", self.state.get("outputDirectory", "."))
        os.makedirs(log_dir, exist_ok=True)
        return os.path.join(log_dir, name or self.cache.get("latest_nn_state", "latest.ckpt"))

    def save_checkpoint(self, name=None, full_path=None, save_optimizer=True,
                        extra=None):
        """Serialize ALL models (+ optimizers) — every entry of the dict.

        ``extra`` is a JSON-able dict stored alongside (epoch counters, score
        logs) — what makes a checkpoint a full mid-run resume point, which
        the reference cannot do (SURVEY §5 "no mid-run resume")."""
        payload = {
            "source": CHECKPOINT_SOURCE,
            "models": flax.serialization.to_state_dict(
                jax.device_get(self.train_state.params)
            ),
            "step": int(self.train_state.step),
            "rng": np.asarray(jax.device_get(self.train_state.rng)),
        }
        if extra is not None:
            payload["extra"] = extra
        if save_optimizer:
            # optax states are namedtuple chains; flatten to plain dicts
            payload["optimizers"] = flax.serialization.to_state_dict(
                jax.device_get(self.train_state.opt_state)
            )
        path = full_path or self.checkpoint_path(name)
        # atomic: a crash mid-write can never truncate the previous good
        # checkpoint (these files are the crash-resume points)
        with _telemetry().span(
            "checkpoint:save", cat="io", file=os.path.basename(path)
        ):
            atomic_write(path, flax.serialization.msgpack_serialize(payload))
        return path

    def load_checkpoint(self, name=None, full_path=None, load_optimizer=True,
                        allow_torch=True):
        path = full_path or self.checkpoint_path(name)
        from ..utils.torch_import import is_torch_file

        if is_torch_file(path):
            if not allow_torch:
                # wire-received files (aggregator pretrain broadcast) are
                # always this framework's own msgpack checkpoints; a torch
                # pickle arriving there is at best a misconfiguration and at
                # worst an attack on the sites — never deserialize it
                raise RuntimeError(
                    f"{path!r} is a torch checkpoint, but torch import is "
                    "only allowed for operator-configured local files "
                    "(cache['pretrained_path']), not files received from "
                    "the aggregator"
                )
            return self._load_torch_checkpoint(path, load_optimizer)
        with open(path, "rb") as f:
            payload = flax.serialization.msgpack_restore(f.read())
        self.last_checkpoint_extra = dict(payload.get("extra", {}))
        if payload.get("source") == CHECKPOINT_SOURCE:
            models = payload["models"]
        else:
            # foreign checkpoint: best-effort — treat the whole payload as a
            # params dict (ref non-coinstac fallback ``basetrainer.py:76-99``)
            models = payload
        if self.train_state is None:
            self._params = models
            return self
        params = {n: flax.serialization.from_state_dict(self.train_state.params[n], models[n])
                  for n in self.train_state.params}
        opt_state = self.train_state.opt_state
        if load_optimizer and "optimizers" in payload:
            opt_state = flax.serialization.from_state_dict(opt_state, payload["optimizers"])
        step = self.train_state.step
        if "step" in payload:
            step = jnp.asarray(int(payload["step"]), jnp.int32)
        rng = self.train_state.rng
        if "rng" in payload:
            rng = jnp.asarray(np.asarray(payload["rng"]), jnp.uint32)
        self.train_state = self.train_state.replace(
            params=params, opt_state=opt_state, step=step, rng=rng
        )
        return self

    def _load_torch_checkpoint(self, path, load_optimizer=True):
        """Warm-start (or optimizer-carrying resume) from a reference-
        ecosystem torch checkpoint (``weights.tar`` written by torch.save —
        ref ``nn/basetrainer.py:76-99``).  Model weights always import; for
        a coinstac-format payload carrying per-model Adam optimizer state
        the moments graft onto the optax state too (the reference loads
        optimizer state dicts, ``:84-93``) — otherwise each imported
        model's optimizer restarts fresh, the standard warm-start.  Models
        absent from the checkpoint keep their current weights and
        optimizer state.  ``cache['torch_name_map']`` ({torch name:
        'flax/param/path'}) overrides positional pairing for divergent
        definition orders; ``cache['import_torch_optimizer']=False``
        forces the fresh-optimizer warm start."""
        from ..utils.torch_import import (
            _convert_checkpoint_with_opts, convert_torch_adam_state,
            graft_adam_state,
        )

        self.last_checkpoint_extra = {}
        name_map = self.cache.get("torch_name_map") or None
        # Positional pairing needs the CREATION-ordered tree (params that
        # have been through a jitted step come back with dict keys sorted,
        # bias before kernel) — use init_nn's ``_params``, or rebuild one
        # from the modules on the steady-state partial-init path.
        template = getattr(self, "_params", None)
        if template is None and self.nn:
            template = self._creation_ordered_params()
        if template is None:
            raise RuntimeError(
                "torch checkpoint import needs initialized models — call "
                "init_nn() before load_checkpoint() on a torch file"
            )
        imported, torch_opts = _convert_checkpoint_with_opts(
            template, path, name_map=name_map,
            allow_unsafe=bool(self.cache.get("allow_unsafe_torch_pickle")),
        )
        if self.train_state is None:
            self._params = {**template, **imported}
            return self
        params = dict(self.train_state.params)
        params.update(imported)
        # fresh optimizer per imported model (stale moments for replaced
        # weights must never apply) — then graft the checkpoint's torch
        # Adam moments onto it when present and convertible
        opt_state = dict(self.train_state.opt_state)
        want_opt = load_optimizer and self.cache.get(
            "import_torch_optimizer", True
        )
        grafted_counts = []
        for n in imported:
            opt_state[n] = self.optimizer[n].init(imported[n])
            opt_sd = torch_opts.get(n)
            if not (want_opt and opt_sd):
                continue
            try:
                mu, nu, count = convert_torch_adam_state(
                    template[n], opt_sd, name_map=name_map
                )
                opt_state[n] = graft_adam_state(opt_state[n], mu, nu, count)
                grafted_counts.append(count)
            except (ValueError, KeyError, TypeError) as exc:
                logger.warn(
                    f"torch optimizer state for {n!r} not imported ({exc}); "
                    "starting that optimizer fresh"
                )
        # a true resume carries the step forward too: anything keyed on
        # train_state.step (LR schedules, step-based logging) continues
        # from the imported optimizer count.  A plain warm start (no
        # optimizer graft) restarts at step 0.
        step = jnp.asarray(max(grafted_counts, default=0), jnp.int32)
        self.train_state = self.train_state.replace(
            params=params, opt_state=opt_state, step=step,
        )
        return self

    # -------------------------------------------------------- compiled kernels
    def _metrics_shell(self):
        return self.new_metrics(), self.new_averages()

    def _note_jit_build(self, key):
        """Telemetry marker: a compiled step is about to be (re)traced and
        built — paired with the jax.monitoring compile-duration bridge this
        is the per-round recompile counter.  Host-side only: this must
        never be called from inside the traced function itself (the
        ``trace-telemetry`` dinulint rule enforces it)."""
        _telemetry().event(
            "jit_build", cat="compile", fn=str(key),
            trainer=type(self).__qualname__,
        )

    def _note_jit_cost(self, key, fn, args):
        """Perf flight recorder: XLA cost analysis (flops, bytes accessed)
        of a freshly built executable, as a ``jit_cost`` event + the flops
        registry feeding the per-round achieved-TFLOPS/MFU series
        (telemetry/perf.py).  One extra trace per build when telemetry is
        enabled; nothing otherwise."""
        rec = _telemetry()
        if rec.enabled:
            _perf.record_jit_cost(self.cache, str(key), fn, args,
                                  recorder=rec)

    def _perf_round_end(self, timer, key, stacked, rec, built=False):
        """Per-round perf bookkeeping after the step's host fence: the
        samples/s + achieved-TFLOPS/MFU series and one device-memory
        sample (leak/pressure detectors).  ``stacked`` carries the padded
        (k, B, ...) batch the step consumed.  ``built`` marks the round
        that (re)compiled the executable: its wall time is XLA compile
        time, not a step — recording it would put a ~1000x-low sample at
        the head of every throughput series (the ``jit_cost`` event
        already marks the build), so only the memory sample is kept."""
        if not built:
            leaf = jax.tree_util.tree_leaves(stacked)[0]
            timer.done(self.cache, key,
                       int(leaf.shape[0]) * int(leaf.shape[1]),
                       recorder=rec)
        _perf.sample_device_memory(self.cache, recorder=rec)

    # ---- local multi-device data parallelism ----------------------------
    # ≙ the reference's automatic torch.nn.DataParallel fan-out over a
    # site's GPUs (ref ``nn/basetrainer.py:62-74``): train/eval steps shard
    # the batch over every local device via shard_map; the mask-weighted
    # gradient reduction keeps numerics identical to single-device.
    # Opt out with ``cache['local_data_parallel'] = False``; cap the device
    # count with ``cache['local_devices']``.
    @staticmethod
    def make_grad_reduce(axis):
        """Mask-weighted mean over ``axis`` device shards of one micro-batch —
        reproduces the full-batch masked-mean gradient exactly even when the
        padded tail splits unevenly across shards."""

        def grad_reduce(g, batch):
            mask = batch.get("_mask")
            n = (jnp.sum(jnp.asarray(mask, jnp.float32)) if mask is not None
                 else jnp.asarray(
                     jax.tree_util.tree_leaves(batch)[0].shape[0], jnp.float32))
            denom = jnp.maximum(jax.lax.psum(n, axis), 1.0)
            return jax.tree_util.tree_map(
                lambda x: jax.lax.psum(x * n, axis) / denom, g
            )

        return grad_reduce

    def _dp_device_count(self, batch_dim):
        """Largest local-device count that divides the (static, padded) batch
        dimension; 1 disables the data-parallel path."""
        if self.cache.get("local_data_parallel", True) is False:
            return 1
        n = len(jax.devices())
        cap = self.cache.get("local_devices")
        if cap:
            n = min(n, int(cap))
        while n > 1 and batch_dim % n:
            n -= 1
        return max(n, 1)

    def _dp_mesh(self, n):
        from jax.sharding import Mesh

        return Mesh(np.array(jax.devices()[:n]), (MeshAxis.DEVICE,))

    def _reduce_dp_aux(self, aux, stacked):
        aux = dict(aux)
        if aux.get("metrics") is not None:
            aux["metrics"] = jax.lax.psum(aux["metrics"], MeshAxis.DEVICE)
        if "host_scores" in aux:
            aux["host_scores"] = jax.tree_util.tree_map(
                lambda x: jax.lax.all_gather(x, MeshAxis.DEVICE, axis=0, tiled=True),
                aux["host_scores"],
            )
        aux["averages"] = jax.lax.psum(aux["averages"], MeshAxis.DEVICE)
        # weight the reported loss by each shard's real-sample count; for a
        # single micro-batch this reproduces the single-device full-batch
        # masked mean exactly (with grad accumulation the per-micro-batch
        # weights are approximated by the shard total — display-only; the
        # epoch averages state stays exact either way)
        mask = stacked.get("_mask")
        if mask is not None:
            n = jnp.sum(jnp.asarray(mask, jnp.float32))
            aux["loss"] = jax.lax.psum(aux["loss"] * n, MeshAxis.DEVICE) / jnp.maximum(
                jax.lax.psum(n, MeshAxis.DEVICE), 1.0
            )
        else:
            aux["loss"] = jax.lax.pmean(aux["loss"], MeshAxis.DEVICE)
        return aux

    @staticmethod
    def _zeros_f32(tree):
        """f32 device-side zero state (host empty_state() is f64 numpy)."""
        return jax.tree_util.tree_map(
            lambda x: jnp.zeros_like(jnp.asarray(x, jnp.float32)), tree
        )

    def _step_outputs(self, it, batch, metrics_shell, averages_shell):
        """Metric/average state deltas for one micro-batch, inside jit."""
        mask = batch.get("_mask")
        m_state = None
        if "pred" in it and "true" in it and getattr(metrics_shell, "jit_safe", True):
            m_state = metrics_shell.update_state(
                self._zeros_f32(metrics_shell.empty_state()), it["pred"], it["true"], mask
            )
        vals = it.get("averages", it["loss"])
        n = jnp.sum(mask) if mask is not None else jnp.asarray(
            next(iter(batch.values())).shape[0], jnp.float32
        )
        a_state = averages_shell.update_state(
            self._zeros_f32(averages_shell.empty_state()), vals, n
        )
        return m_state, a_state

    def _apply_updates(self, ts, grads):
        new_params, new_opt = {}, {}
        for name in ts.params:
            updates, new_opt[name] = self.optimizer[name].update(
                grads[name], ts.opt_state[name], ts.params[name]
            )
            new_params[name] = optax.apply_updates(ts.params[name], updates)
        return ts.replace(
            params=new_params, opt_state=new_opt, step=ts.step + 1
        )

    def compute_grads(self, ts, stacked_batches):
        """Mean gradients over ``local_iterations`` stacked micro-batches via
        ``lax.scan`` (compiled grad accumulation).  Returns (grads, aux).
        This is the site-side half of a federated round (≙ learner.backward).
        With >1 local device the batch fans out over a ``device`` mesh axis
        (≙ ref DataParallel) and the returned grads are the exact masked-mean."""
        rec = _telemetry()
        rec.count("grad_steps")
        n = self._dp_device_count(
            jax.tree_util.tree_leaves(stacked_batches)[0].shape[1]
        )
        # perf flight recorder: time the round (the grad-health norm below
        # is the host fence) and wrap it in the profiler when an anomaly
        # armed a deep capture (telemetry/capture.py) — both enabled-only
        timer = _perf.StepTimer() if rec.enabled else None
        cm = (_capture.captured_round(
                  self.cache, self.state.get("outputDirectory"), rec)
              if rec.enabled else contextlib.nullcontext())
        built = False
        with cm:
            if n > 1:
                key = f"grads_dp:{n}"
                built = ("grads_dp", n) not in self._compiled
                grads, aux = self._compute_grads_dp(ts, stacked_batches, n)
            else:
                key = "grads"
                fn = self._compiled.get("grads")
                if fn is None:
                    built = True
                    self._note_jit_build("grads")
                    metrics_shell, averages_shell = self._metrics_shell()

                    def _grads(ts, stacked):
                        return self._grads_uncompiled(ts, stacked, metrics_shell, averages_shell)

                    fn = self._compiled["grads"] = jax.jit(_grads)
                    self._note_jit_cost("grads", fn, (ts, stacked_batches))
                grads, aux = fn(ts, stacked_batches)
            if rec.enabled:
                # host-side, AROUND the compiled call: global grad norm +
                # its watchdog EMA + the round's mean loss — the host sync
                # also fences the step for the timer (docs/TELEMETRY.md)
                _health.record_grad_health(self.cache, grads, aux, recorder=rec)
        if timer is not None:
            self._perf_round_end(timer, key, stacked_batches, rec, built=built)
        return grads, aux

    def _build_dp_step(self, n, apply_updates, donate):
        """Compiled batch-sharded step over ``n`` local devices: per-shard
        decorrelated dropout streams, mask-weighted gradient reduction, and
        an identically-advancing carried rng (replication invariant).  With
        ``apply_updates`` the optimizer runs in-step (train); without, the
        reduced grads return to the caller (federated backward)."""
        from jax.sharding import PartitionSpec as P

        metrics_shell, averages_shell = self._metrics_shell()
        grad_reduce = self.make_grad_reduce(MeshAxis.DEVICE)

        def shard_step(ts, stacked):
            # both split halves are consumed (num-prng-discard): [0]
            # carries — identically on every shard, and bit-identical to
            # the historical split(rng)[0] advance — while [1] seeds the
            # per-shard decorrelated streams, so the parent key is never
            # consumed twice
            next_rng, shard_rng = jax.random.split(ts.rng)
            ts = ts.replace(
                rng=jax.random.fold_in(shard_rng, jax.lax.axis_index(MeshAxis.DEVICE))
            )
            grads, aux = self._grads_uncompiled(
                ts, stacked, metrics_shell, averages_shell,
                grad_reduce=grad_reduce,
            )
            aux = self._reduce_dp_aux(aux, stacked)
            aux["rng"] = next_rng
            if not apply_updates:
                return grads, aux
            ts = self._apply_updates(ts, grads)
            ts = ts.replace(rng=aux["rng"])
            return ts, aux

        return jax.jit(
            shard_map(
                shard_step, mesh=self._dp_mesh(n),
                in_specs=(P(), P(None, MeshAxis.DEVICE)), out_specs=(P(), P()),
                check_vma=False,
            ),
            donate_argnums=donate,
        )

    def _compute_grads_dp(self, ts, stacked_batches, n):
        fn = self._compiled.get(("grads_dp", n))
        if fn is None:
            self._note_jit_build(f"grads_dp:{n}")
            fn = self._compiled[("grads_dp", n)] = self._build_dp_step(
                n, apply_updates=False, donate=()
            )
            self._note_jit_cost(f"grads_dp:{n}", fn, (ts, stacked_batches))
        return fn(ts, stacked_batches)

    def apply_grads(self, ts, grads, new_rng=None):
        """One optimizer step from externally supplied (e.g. averaged)
        gradients — the site-side apply half of a federated round."""
        rec = _telemetry()
        if rec.enabled:
            _health.record_update_health(self.cache, grads, recorder=rec)
        fn = self._compiled.get("apply")
        if fn is None:
            self._note_jit_build("apply")
            fn = self._compiled["apply"] = jax.jit(self._apply_updates)
        ts = fn(ts, grads)
        if new_rng is not None:
            ts = ts.replace(rng=new_rng)
        return ts

    def train_step(self, ts, stacked_batches):
        """compute_grads + apply_grads fused in one compiled call (the local
        hot path — nothing leaves the device between grad and update).

        On accelerator backends the incoming ``ts`` is DONATED: its buffers
        are reused for the result, so the caller must treat the passed-in
        state as consumed (rebind: ``ts, aux = trainer.train_step(ts, ...)``).
        On CPU donation is a no-op, so code that re-reads the old state only
        breaks on TPU/GPU — set ``cache['donate_buffers'] = False`` to opt
        out everywhere.

        With >1 local device the batch shards over a ``device`` mesh axis
        (≙ the reference's automatic DataParallel, ``nn/basetrainer.py:
        62-74``); the mask-weighted reduction keeps the update identical to
        the single-device step (up to per-shard dropout streams)."""
        rec = _telemetry()
        rec.count("train_steps")
        n = self._dp_device_count(
            jax.tree_util.tree_leaves(stacked_batches)[0].shape[1]
        )
        timer = _perf.StepTimer() if rec.enabled else None
        cm = (_capture.captured_round(
                  self.cache, self.state.get("outputDirectory"), rec)
              if rec.enabled else contextlib.nullcontext())
        built = False
        with cm:
            if n > 1:
                key = f"train_dp:{n}"
                built = ("train_dp", n) not in self._compiled
                out = self._train_step_dp(ts, stacked_batches, n)
            else:
                key = "train"
                fn = self._compiled.get("train")
                if fn is None:
                    built = True
                    self._note_jit_build("train")
                    fn = self._compiled["train"] = self._build_train_step()
                    self._note_jit_cost("train", fn, (ts, stacked_batches))
                out = fn(ts, stacked_batches)
            if timer is not None:
                # one scalar fence per round: the flight recorder trades a
                # sliver of pipelining for honest wall time (enabled only)
                jax.block_until_ready(out[1]["loss"])
        if timer is not None:
            self._perf_round_end(timer, key, stacked_batches, rec, built=built)
        return out

    def _build_train_step(self):
        """The fused grad+update jit — the single-device production hot
        path.  The incoming train state is DONATED on accelerator backends
        (params/opt buffers update in place instead of doubling HBM; see
        :func:`~..utils.jax_compat.resolve_donate_argnums` — the decision
        dinulint tier-3's ``perf-donation`` rule audits via the
        'trainer-train-jit' entry)."""
        metrics_shell, averages_shell = self._metrics_shell()

        def _full(ts, stacked):
            grads, aux = self._grads_uncompiled(
                ts, stacked, metrics_shell, averages_shell
            )
            ts = self._apply_updates(ts, grads)
            ts = ts.replace(rng=aux["rng"])
            return ts, aux

        return jax.jit(
            _full, donate_argnums=resolve_donate_argnums(self.cache, (0,))
        )

    def _train_step_dp(self, ts, stacked_batches, n):
        fn = self._compiled.get(("train_dp", n))
        if fn is None:
            self._note_jit_build(f"train_dp:{n}")
            fn = self._compiled[("train_dp", n)] = self._build_dp_step(
                n, apply_updates=True,
                donate=resolve_donate_argnums(self.cache, (0,)),
            )
            self._note_jit_cost(f"train_dp:{n}", fn, (ts, stacked_batches))
        return fn(ts, stacked_batches)

    def _grads_uncompiled(self, ts, stacked, metrics_shell, averages_shell,
                          grad_reduce=None, iteration_fn=None):
        """``grad_reduce(g, batch) -> g``: optional per-micro-batch gradient
        reduction applied INSIDE the scan — the hook data-parallel wrappers
        use to mask-weight-average shard gradients over a device axis so a
        padded batch split unevenly across devices still yields exactly the
        full-batch masked-mean gradient (see ``parallel/mesh.py``).
        ``iteration_fn`` overrides ``self.iteration`` (the sequence-parallel
        mesh passes the sp-aware variant)."""
        # non-jit-safe metrics (AUC) can't accumulate on device — carry the
        # per-microbatch scores out of the scan so the host can feed them
        collect_host = not getattr(metrics_shell, "jit_safe", True)
        it_fn = iteration_fn if iteration_fn is not None else self.iteration

        def loss_fn(params, batch, rng):
            it = it_fn(params, batch, rng)
            return it["loss"], it

        def body(carry, batch):
            rng, gsum, msum, asum = carry
            rng, sub = jax.random.split(rng)
            (loss, it), g = jax.value_and_grad(loss_fn, has_aux=True)(ts.params, batch, sub)
            if grad_reduce is not None:
                g = grad_reduce(g, batch)
            m_state, a_state = self._step_outputs(it, batch, metrics_shell, averages_shell)
            gsum = jax.tree_util.tree_map(jnp.add, gsum, g)
            if m_state is not None:
                msum = jax.tree_util.tree_map(jnp.add, msum, m_state)
            asum = jax.tree_util.tree_map(jnp.add, asum, a_state)
            ys = {"loss": loss}
            if collect_host:
                hs = self.host_scores_payload(it, batch)
                if hs is not None:
                    ys["host_scores"] = hs
            return (rng, gsum, msum, asum), ys

        k = jax.tree_util.tree_leaves(stacked)[0].shape[0]
        gsum0 = jax.tree_util.tree_map(jnp.zeros_like, ts.params)
        m0 = self._zeros_f32(metrics_shell.empty_state())
        a0 = self._zeros_f32(averages_shell.empty_state())
        if k == 1:
            # no grad accumulation: skip the lax.scan machinery (its carry
            # staging costs ~0.5 ms/step on the flagship); same math, and
            # ys keeps the (k,) leading axis consumers expect
            carry, ys1 = body(
                (ts.rng, gsum0, m0, a0),
                {kk: v[0] for kk, v in stacked.items()},
            )
            rng, gsum, msum, asum = carry
            ys = jax.tree_util.tree_map(lambda y: y[None], ys1)
        else:
            (rng, gsum, msum, asum), ys = jax.lax.scan(
                body, (ts.rng, gsum0, m0, a0), stacked
            )
        grads = jax.tree_util.tree_map(lambda g: g / k, gsum)
        # a non-jit-safe metric's device state is meaningless — report None so
        # callers fall through to the host_scores path
        aux = {"rng": rng, "metrics": (None if collect_host else msum),
               "averages": asum, "loss": jnp.mean(ys["loss"])}
        if "host_scores" in ys:
            aux["host_scores"] = ys["host_scores"]
        return grads, aux

    def eval_step(self, ts, batch):
        _telemetry().count("eval_steps")
        n = self._dp_device_count(jax.tree_util.tree_leaves(batch)[0].shape[0])
        if n > 1:
            return self._eval_step_dp(ts, batch, n)
        fn = self._compiled.get("eval")
        if fn is None:
            self._note_jit_build("eval")
            metrics_shell, averages_shell = self._metrics_shell()

            def _eval(ts, batch):
                it = self.iteration(ts.params, batch, None)
                m_state, a_state = self._step_outputs(it, batch, metrics_shell, averages_shell)
                return m_state, a_state, it

            fn = self._compiled["eval"] = jax.jit(_eval)
            self._note_jit_cost("eval", fn, (ts, batch))
        return fn(ts, batch)

    def _eval_step_dp(self, ts, batch, n):
        from jax.sharding import PartitionSpec as P

        fn = self._compiled.get(("eval_dp", n))
        if fn is None:
            self._note_jit_build(f"eval_dp:{n}")
            metrics_shell, averages_shell = self._metrics_shell()

            def shard_eval(ts, batch):
                it = self.iteration(ts.params, batch, None)
                m_state, a_state = self._step_outputs(
                    it, batch, metrics_shell, averages_shell
                )
                if m_state is not None:
                    m_state = jax.lax.psum(m_state, MeshAxis.DEVICE)
                a_state = jax.lax.psum(a_state, MeshAxis.DEVICE)
                # carry the FULL it dict through (the hook's contract is
                # "anything else is carried through"): per-sample arrays
                # gather back into full-batch order (host-side AUC +
                # save_predictions rely on it), scalars average
                shard_b = jax.tree_util.tree_leaves(batch)[0].shape[0]
                out_it = {}
                for k, v in it.items():
                    arr = jnp.asarray(v)
                    if arr.ndim >= 1 and arr.shape[0] == shard_b:
                        out_it[k] = jax.lax.all_gather(
                            arr, MeshAxis.DEVICE, axis=0, tiled=True
                        )
                    elif arr.ndim == 0:
                        out_it[k] = jax.lax.pmean(arr, MeshAxis.DEVICE)
                    else:
                        out_it[k] = arr  # replicated (e.g. per-class stats)
                return m_state, a_state, out_it

            fn = self._compiled[("eval_dp", n)] = jax.jit(
                shard_map(
                    shard_eval, mesh=self._dp_mesh(n),
                    in_specs=(P(), P(MeshAxis.DEVICE)), out_specs=(P(), P(), P()),
                    check_vma=False,
                )
            )
            self._note_jit_cost(f"eval_dp:{n}", fn, (ts, batch))
        return fn(ts, batch)

    # ----------------------------------------------------------- train / eval
    def _input_cast_dtype(self):
        """dtype that float ``inputs`` are cast to at batch-staging time, or
        None.  Pure perf move with identical math: every shipped model's
        first op is ``jnp.asarray(x, dtype)``, so casting at staging computes
        the same values while halving the batch's HBM traffic inside the
        step — the forward conv AND its kernel-gradient each re-read the
        batch (measured ~0.9 ms/step on the flagship at batch 128·64³).
        ``cache['cast_inputs']=False`` opts out for custom models that do
        float32 math on raw inputs before casting; a trainer class can also
        set ``CAST_INPUTS = False`` to change its own default (the cache key,
        when present, always wins)."""
        if not self.cache.get("cast_inputs", type(self).CAST_INPUTS):
            return None
        dt = jnp.dtype(self.cache.get("compute_dtype", "float32"))
        return None if dt == jnp.float32 else dt

    def _cast_batch_inputs(self, batch, cast=None):
        """Apply the staging cast (:meth:`_input_cast_dtype`) to a batch
        dict's ``inputs`` leaf.  Works on host (numpy) and device (jax)
        arrays alike — call it on host batches BEFORE the device transfer so
        the copy ships half the bytes."""
        cast = self._input_cast_dtype() if cast is None else cast
        v = batch.get("inputs") if cast is not None else None
        if v is None:
            return batch
        arr = v if hasattr(v, "dtype") else np.asarray(v)
        if jnp.issubdtype(arr.dtype, jnp.floating) and arr.dtype != cast:
            batch = dict(batch)
            batch["inputs"] = arr.astype(np.dtype(cast))
        return batch

    def _stack_batches(self, batches):
        """[k dict batches] -> dict of (k, B, ...) arrays for lax.scan.

        Casts each batch BEFORE stacking so host batches cross to the device
        already in the compute dtype (half the transfer bytes)."""
        cast = self._input_cast_dtype()
        if cast is not None:
            batches = [self._cast_batch_inputs(b, cast) for b in batches]
        keys = batches[0].keys()
        return {k: jnp.stack([jnp.asarray(b[k]) for b in batches]) for k in keys}

    def training_iteration_local(self, batches):
        """One communication round locally: grad-accumulate over the batch
        list, step the optimizer, return host-side it dict."""
        stacked = self._stack_batches(batches)
        self.train_state, aux = self.train_step(self.train_state, stacked)
        return aux

    @staticmethod
    def host_scores_payload(it, batch):
        """(score, true, mask) f32 payload for host-side (non-jit-safe)
        metric accumulation, or None when the iteration lacks pred/true.
        ``score`` prefers the calibrated ``prob`` over argmax labels."""
        if "pred" not in it or "true" not in it:
            return None
        mask = batch.get("_mask")
        true = jnp.asarray(it["true"], jnp.float32)
        return {
            "score": jnp.asarray(it.get("prob", it["pred"]), jnp.float32),
            "true": true,
            "mask": (jnp.asarray(mask, jnp.float32) if mask is not None
                     else jnp.ones(true.shape, jnp.float32)),
        }

    @staticmethod
    def fold_train_outputs(aux, ep_averages, ep_metrics):
        """Fold one round's aux into the epoch accumulators — device states
        for jit-safe metrics, the carried-out ``host_scores`` otherwise."""
        ep_averages.update(aux["averages"])
        if aux.get("metrics") is not None:
            ep_metrics.update(aux["metrics"])
        elif "host_scores" in aux:
            hs = aux["host_scores"]
            ep_metrics.add(
                np.asarray(hs["score"]), np.asarray(hs["true"]),
                mask=np.asarray(hs["mask"]),
            )

    def evaluation(self, mode=Mode.VALIDATION, dataset_list=None, save_pred=False,
                   distributed=False):
        """No-grad loop over one or more datasets with mask-weighted metrics."""
        metrics, averages = self.new_metrics(), self.new_averages()
        datasets = dataset_list if dataset_list is not None else [
            self.data_handle.datasets.get(str(mode), None)
        ]
        for ds in datasets:
            if ds is None or len(ds) == 0:
                continue
            loader = self.data_handle.get_loader(
                handle_key=str(mode), dataset=ds, shuffle=False
            )
            ds_metrics, ds_averages = self.new_metrics(), self.new_averages()
            predictions = []  # per-dataset (sparse test = one file per subject)
            for batch in loader:
                # cast host-side first: the transfer then ships half the bytes
                batch = self._cast_batch_inputs(batch)
                batch = {k: jnp.asarray(v) for k, v in batch.items()}
                m_state, a_state, it = self.eval_step(self.train_state, batch)
                if m_state is not None:
                    ds_metrics.update(m_state)
                elif not ds_metrics.jit_safe and "pred" in it and "true" in it:
                    # variable-shape metrics (AUC) accumulate host-side;
                    # probability-ranked metrics read ``prob`` when the
                    # iteration provides it (argmax labels collapse AUC)
                    ds_metrics.add(
                        np.asarray(it.get("prob", it["pred"])), np.asarray(it["true"]),
                        mask=np.asarray(batch.get("_mask")) if "_mask" in batch else None,
                    )
                ds_averages.update(a_state)
                if save_pred and "pred" in it:
                    predictions.append(
                        (np.asarray(it["pred"]), np.asarray(batch.get("_mask")))
                    )
            metrics.accumulate(ds_metrics)
            averages.accumulate(ds_averages)
            if save_pred:
                self.save_predictions(ds, predictions)
        return averages, metrics

    _RESUME_KEYS = ("train_log", "validation_log", "best_val_epoch",
                    "best_val_score")

    def train_local(self, train_dataset=None, val_dataset=None):
        """Full local training loop: epochs × batches with validation cadence,
        best-checkpoint save, early stop, score logging (ref ``:192-243``).

        With ``cache['resume']`` truthy, restarts mid-run from the latest
        autosaved checkpoint: params, optimizer, rng, epoch counter and score
        logs all resume — capability the reference lacks (SURVEY §5, cache
        state dies with the process there).  Autosave cadence:
        ``cache['autosave_epochs']`` (default every epoch)."""
        cache = self.cache
        epochs = int(cache.get("epochs", 10))
        local_iterations = int(cache.get("local_iterations", 1))
        cache.setdefault("train_log", [])
        cache.setdefault("validation_log", [])
        if train_dataset is None:
            train_dataset = self.data_handle.get_train_dataset()
        if val_dataset is None:
            val_dataset = self.data_handle.get_validation_dataset()

        start_epoch = 1
        if cache.get("resume"):
            ckpt = self.checkpoint_path(cache.get("latest_nn_state", "latest.ckpt"))
            if os.path.exists(ckpt):
                self.load_checkpoint(full_path=ckpt)
                extra = getattr(self, "last_checkpoint_extra", {})
                for k in self._RESUME_KEYS:
                    if k in extra:
                        cache[k] = extra[k]
                start_epoch = int(extra.get("epoch", 0)) + 1
                logger.info(
                    f"Resuming from epoch {start_epoch}", cache.get("verbose", True)
                )

        from ..data import device_prefetch

        for epoch in range(start_epoch, epochs + 1):
            ep_averages, ep_metrics = self.new_averages(), self.new_metrics()
            loader = self.data_handle.get_loader(
                "train", dataset=train_dataset, shuffle=True,
                seed=int(cache.get("seed", 0)), epoch=epoch, drop_last=False,
            )
            # stay a couple of batches ahead: the host→device copy of batch
            # i+1 overlaps the compiled step on batch i; with local DP the
            # batch lands pre-sharded over the device mesh (no re-shard hop)
            n_dp = self._dp_device_count(int(cache.get("batch_size", 16)))
            shard = None
            if n_dp > 1:
                from jax.sharding import NamedSharding, PartitionSpec

                shard = NamedSharding(self._dp_mesh(n_dp), PartitionSpec(MeshAxis.DEVICE))
            batch_iter = iter(loader)
            cast = self._input_cast_dtype()
            if cast is not None:
                # cast float inputs on the host BEFORE the transfer: halves
                # the host→device bytes in flight and lands the batch in the
                # dtype the model's first op would cast to anyway
                def _cast_iter(src):
                    for b in src:
                        yield self._cast_batch_inputs(b, cast)

                batch_iter = _cast_iter(batch_iter)
            batches = device_prefetch(
                batch_iter, size=int(cache.get("prefetch_batches", 2)),
                sharding=shard,
            )
            batch_buf = []
            for i, batch in enumerate(batches):
                batch_buf.append(batch)
                if len(batch_buf) == local_iterations:
                    aux = self.training_iteration_local(batch_buf)
                    self.fold_train_outputs(aux, ep_averages, ep_metrics)
                    batch_buf = []
                    if logger.lazy_debug(i):
                        logger.info(
                            f"Ep {epoch}/{epochs} it {i}: loss {float(aux['loss']):.4f}",
                            cache.get("verbose", True),
                        )
            if batch_buf:
                aux = self.training_iteration_local(batch_buf)
                self.fold_train_outputs(aux, ep_averages, ep_metrics)
            cache["train_log"].append(ep_averages.get() + ep_metrics.get())

            if epoch % int(cache.get("validation_epochs", 1)) == 0 and len(val_dataset):
                val_averages, val_metrics = self.evaluation(
                    Mode.VALIDATION, [val_dataset]
                )
                cache["validation_log"].append(val_averages.get() + val_metrics.get())
                self._on_validation_end(epoch, val_averages, val_metrics)
                if self._stop_early(epoch):
                    logger.info(f"Early stop at epoch {epoch}", cache.get("verbose", True))
                    break
            autosave_every = int(cache.get("autosave_epochs", 1))
            if autosave_every > 0 and epoch % autosave_every == 0:
                self._autosave(epoch)
        self._on_train_end()
        return self

    def _autosave(self, epoch):
        """Write the latest checkpoint as a full resume point."""
        extra = {"epoch": epoch}
        extra.update({
            k: self.cache[k] for k in self._RESUME_KEYS if k in self.cache
        })
        self.save_checkpoint(
            name=self.cache.get("latest_nn_state", "latest.ckpt"), extra=extra
        )

    # ------------------------------------------------------------- user hooks
    def _on_validation_end(self, epoch, averages, metrics):
        monitor = self.cache.get("monitor_metric", "f1")
        try:
            score = metrics.extract(monitor)
        except AttributeError:
            score = averages.average
        if performance_improved_(epoch, score, self.cache):
            self.save_checkpoint(name=self.cache.get("best_nn_state", "best.ckpt"))

    def _stop_early(self, epoch):
        return stop_training_(epoch, self.cache)

    def _on_train_end(self):
        # keep the resume record: a bare save here would clobber the autosave's
        # epoch counter and make a later resume restart from epoch 1
        self._autosave(len(self.cache.get("train_log", [])))

    def save_predictions(self, dataset, predictions):
        """User hook: persist per-dataset predictions (sparse test mode)."""

    def on_iteration_end(self, it=None):
        """User hook after each communication round."""
