from .basetrainer import CHECKPOINT_SOURCE, NNTrainer, TrainState, seeded_rng

__all__ = ["NNTrainer", "TrainState", "seeded_rng", "CHECKPOINT_SOURCE"]
